"""IMDB sentiment readers (python/paddle/dataset/imdb.py parity):
word_dict() builds token->id from the aclImdb tarball; train(word_dict)/
test(word_dict) yield ([word ids], label 0/1). Offline fallback: two
token distributions (positive/negative vocab halves) — learnable by the
bow/lstm book models."""

import re
import string
import tarfile

import numpy as np

from paddle_tpu.dataset import common

URL = "https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz"
MD5 = "7c2ac02c03563afcf9b574c7e56c153a"

_SYN_VOCAB = 200
_SYN_TRAIN, _SYN_TEST = 1500, 300


def _tokenize(text):
    return re.sub(
        "[%s]" % re.escape(string.punctuation), "", text.lower()
    ).split()


def _tar_docs(path, pattern):
    pat = re.compile(pattern)
    with tarfile.open(path, "r:gz") as tf:
        for member in tf.getmembers():
            if member.isfile() and pat.match(member.name):
                yield _tokenize(
                    tf.extractfile(member).read().decode("utf-8", "replace")
                )


def _synthetic_word_dict():
    common.note_synthetic("imdb")
    d = {"w%d" % i: i for i in range(_SYN_VOCAB)}
    d["<unk>"] = len(d)
    return d


def _synthetic_docs(n, seed, word_dict):
    """label 1 docs draw 70% from the low vocab half, label 0 from the
    high half; sequence lengths vary."""
    rng = np.random.RandomState(seed)
    half = _SYN_VOCAB // 2
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 40))
        main_ids = rng.randint(0, half, length)
        if label == 0:
            main_ids = main_ids + half
        flip = rng.rand(length) < 0.3
        noise = rng.randint(0, _SYN_VOCAB, length)
        ids = np.where(flip, noise, main_ids)
        yield [int(i) for i in ids], label


def word_dict():
    path = common.try_download(URL, "imdb", MD5)
    if path is None:
        return _synthetic_word_dict()
    freq = {}
    for pattern in ("aclImdb/train/pos/.*\\.txt$",
                    "aclImdb/train/neg/.*\\.txt$"):
        for doc in _tar_docs(path, pattern):
            for w in doc:
                freq[w] = freq.get(w, 0) + 1
    words = sorted(freq, key=lambda w: (-freq[w], w))
    d = {w: i for i, w in enumerate(words)}
    d["<unk>"] = len(d)
    return d


def _reader(pos_pattern, neg_pattern, syn_n, seed, word_idx):
    def reader():
        path = common.try_download(URL, "imdb", MD5)
        if path is None:
            yield from _synthetic_docs(syn_n, seed, word_idx)
            return
        unk = word_idx.get("<unk>", len(word_idx))
        for label, pattern in ((1, pos_pattern), (0, neg_pattern)):
            for doc in _tar_docs(path, pattern):
                yield [word_idx.get(w, unk) for w in doc], label

    return reader


def train(word_idx):
    return _reader("aclImdb/train/pos/.*\\.txt$",
                   "aclImdb/train/neg/.*\\.txt$", _SYN_TRAIN, 21, word_idx)


def test(word_idx):
    return _reader("aclImdb/test/pos/.*\\.txt$",
                   "aclImdb/test/neg/.*\\.txt$", _SYN_TEST, 22, word_idx)


def fetch():
    common.try_download(URL, "imdb", MD5)
