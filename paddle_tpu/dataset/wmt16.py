"""WMT16 en<->de readers (python/paddle/dataset/wmt16.py parity):
train/test/validation(src_dict_size, trg_dict_size, src_lang) yield dicts
is replaced by the reference's tuple layout (src_ids, trg_ids, trg_next).
Offline fallback mirrors wmt14's invertible toy pair with a different
mapping so models can't share weights across the two datasets."""

from paddle_tpu.dataset import common, wmt14

URL = "http://paddlemodels.bj.bcebos.com/wmt/wmt16.tar.gz"
MD5 = "0c38be43600334966403524a40dcd81e"


def _reader(member_pat, syn_n, seed, dict_size):
    def reader():
        path = common.try_download(URL, "wmt16", MD5)
        if path is None:
            common.note_synthetic("wmt16")
            yield from wmt14._synthetic_pairs(syn_n, seed, dict_size)
        else:
            yield from wmt14._tar_pairs(path, member_pat, dict_size)

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", 1200, 63, min(src_dict_size, trg_dict_size))


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", 200, 64, min(src_dict_size, trg_dict_size))


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("val", 200, 65, min(src_dict_size, trg_dict_size))


def fetch():
    common.try_download(URL, "wmt16", MD5)
