"""Offline dataset fixtures in each dataset's REAL on-disk format.

This zero-egress rig cannot download the book datasets, so convergence
tests and the on-chip convergence proof (tools/convergence_run.py) write
deterministic, learnable fixtures in the native wire formats and push
them through the real file->parser->reader pipeline (tests/
test_book_realdata.py and the tool share these writers so the recipe
cannot drift between them).

Reference analogy: paddle/fluid/inference/tests' test.cmake downloads
pinned artifacts; here the artifact is generated, but the parse path
exercised is the same one real downloads take.
"""

import gzip
import os
import struct

import numpy as np


def write_mnist_idx_fixture(dirname, n, seed, prefix):
    """IDX gzip pair (images magic 2051, labels magic 2049): 10 class
    templates + noise — linearly separable enough for the book
    recognize_digits convergence threshold, deterministic per seed.
    Returns (image_path, label_path)."""
    rng = np.random.RandomState(seed)
    templates = np.random.RandomState(1234).rand(10, 784)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = (0.75 * templates[labels] + 0.25 * rng.rand(n, 784))
    images = (images * 255).astype(np.uint8)
    os.makedirs(dirname, exist_ok=True)
    img_path = os.path.join(dirname, prefix + "-images-idx3-ubyte.gz")
    lbl_path = os.path.join(dirname, prefix + "-labels-idx1-ubyte.gz")
    with gzip.open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(images.tobytes())
    with gzip.open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return img_path, lbl_path
