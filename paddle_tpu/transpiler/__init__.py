"""Graph-to-graph transpilers (python/paddle/fluid/transpiler parity)."""

from paddle_tpu.transpiler.distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from paddle_tpu.transpiler.ps_dispatcher import (  # noqa: F401
    HashName,
    RoundRobin,
)
from paddle_tpu.transpiler.distribute_transpiler import (  # noqa: F401
    slice_variable,
)
from paddle_tpu.transpiler.memory_optimization_transpiler import (  # noqa: F401
    memory_optimize,
    release_memory,
)
from paddle_tpu.transpiler.amp_transpiler import (  # noqa: F401
    rewrite_program_amp,
    amp_guard,
)
from paddle_tpu.transpiler.inference_transpiler import (  # noqa: F401
    InferenceTranspiler,
)
from paddle_tpu.transpiler.quantize_transpiler import (  # noqa: F401
    QuantizeTranspiler,
)
from paddle_tpu.transpiler.gradient_merge_transpiler import (  # noqa: F401
    GradientMergeTranspiler,
    rewrite_program_gradient_merge,
)
