"""Graph-to-graph transpilers (python/paddle/fluid/transpiler parity)."""

from paddle_tpu.transpiler.distribute_transpiler import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from paddle_tpu.transpiler.ps_dispatcher import (  # noqa: F401
    HashName,
    RoundRobin,
)
from paddle_tpu.transpiler.distribute_transpiler import (  # noqa: F401
    slice_variable,
)
