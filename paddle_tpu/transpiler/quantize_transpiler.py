"""Quantization-aware-training transpiler.

Capability parity with the reference's contrib QuantizeTranspiler
(``python/paddle/fluid/contrib/quantize/quantize_transpiler.py``: insert
fake_quantize/fake_dequantize pairs around the quantizable ops for QAT,
then freeze for deployment), redesigned TPU-first:

* ``training_transpile`` runs BEFORE ``optimizer.minimize``: gradients are
  then synthesized from the quantized forward graph by the vjp-based grad
  makers, so the straight-through estimator flows automatically — no
  backward-op input-renaming pass (the reference needs one because its
  backward ops already exist).
* the running activation scale of ``range_abs_max`` is a persistable
  state var updated in-graph (OutScale aliased onto InScale, the
  batch-norm running-stats idiom) instead of a host-managed window
  buffer.
* ``freeze_program`` folds the QAT error into the weights (each quantized
  weight is snapped to its round(w/s * Q)/Q * s grid) and strips the fake
  ops: the deploy program is a plain float program that computes exactly
  what the quantized model computes, which is the right target when the
  deploy compiler is XLA (there is no int8 CPU kernel zoo to feed;
  BASELINE int8 serving is out of the TPU deployment model). The
  weight scales are returned for toolchains that want the int8 tensors.
"""

import numpy as np

__all__ = ["QuantizeTranspiler"]

_QUANTIZABLE_OP_TYPES = ("conv2d", "depthwise_conv2d", "mul")


def _quantized_name(name):
    return "%s.quantized" % name


def _dequantized_name(name):
    return "%s.dequantized" % name


def _scale_name(name):
    return "%s.scale" % name


class QuantizeTranspiler(object):
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        if activation_quantize_type not in ("abs_max", "range_abs_max"):
            raise ValueError(
                "unknown activation_quantize_type %r"
                % (activation_quantize_type,))
        if weight_quantize_type != "abs_max":
            raise ValueError(
                "weights quantize per-batch abs_max (their value IS the "
                "batch); got %r" % (weight_quantize_type,))
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.activation_quantize_type = activation_quantize_type
        self.window_size = window_size

    # -- training ----------------------------------------------------------
    def training_transpile(self, program=None, startup_program=None):
        """Insert fake quant->dequant pairs on every input of the
        quantizable ops. Call BEFORE optimizer.minimize (the backward
        graph is then generated from the quantized forward)."""
        from paddle_tpu import framework

        program = program or framework.default_main_program()
        startup_program = (startup_program
                           or framework.default_startup_program())
        params = {p.name
                  for p in program.global_block().all_parameters()}
        for block in program.blocks:  # sub-blocks (while/cond bodies) too
            self._transpile_block(block, startup_program, params)
        program._bump_version()
        from paddle_tpu.analysis import verify_after_transpile

        verify_after_transpile(program, "QuantizeTranspiler.training_transpile")
        return program

    def _transpile_block(self, block, startup_program, params):
        dequanted = {}  # var name -> dequantized var name (this block)
        idx = 0
        while idx < len(block.ops):
            op = block.ops[idx]
            if op.type not in _QUANTIZABLE_OP_TYPES:
                idx += 1
                continue
            for slot, names in list(op.inputs.items()):
                new_names = []
                for name in names:
                    var = block.var(name)
                    if str(var.dtype) not in ("float32", "float64"):
                        new_names.append(name)
                        continue
                    if name not in dequanted:
                        is_weight = name in params
                        bits = (self.weight_bits if is_weight
                                else self.activation_bits)
                        qtype = ("abs_max" if is_weight
                                 else self.activation_quantize_type)
                        inserted = self._insert_quant_dequant(
                            block, startup_program, idx, name, var, bits,
                            qtype)
                        idx += inserted
                        dequanted[name] = _dequantized_name(name)
                    new_names.append(dequanted[name])
                op.inputs[slot] = new_names
            idx += 1

    def _insert_quant_dequant(self, block, startup_program, idx, name, var,
                              bits, qtype):
        """Insert (at op index idx) the quantize + dequantize ops for
        `name`; returns how many ops were inserted."""
        quant_var = block.create_var(
            name=_quantized_name(name), shape=var.shape, dtype=var.dtype)
        dequant_var = block.create_var(
            name=_dequantized_name(name), shape=var.shape, dtype=var.dtype)
        max_range = float((1 << (bits - 1)) - 1)
        if qtype == "abs_max":
            scale_var = block.create_var(
                name=_scale_name(name), shape=[1], dtype="float32")
            block.insert_op(
                idx,
                type="fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [quant_var.name],
                         "OutScale": [scale_var.name]},
                attrs={"bit_length": bits},
            )
        else:  # range_abs_max: persistable running scale, updated in-graph
            state = block.create_var(
                name="%s.state" % _scale_name(name), shape=[1],
                dtype="float32", persistable=True)
            sb = startup_program.global_block()
            if not sb.has_var(state.name):
                sv = sb.create_var(name=state.name, shape=[1],
                                   dtype="float32", persistable=True)
                from paddle_tpu import initializer

                initializer.ConstantInitializer(1e-3)(sv, sb)
            block.insert_op(
                idx,
                type="fake_quantize_range_abs_max",
                inputs={"X": [name], "InScale": [state.name]},
                outputs={"Out": [quant_var.name],
                         # alias onto the state var: running-stats idiom
                         "OutScale": [state.name]},
                attrs={"bit_length": bits,
                       "window_size": self.window_size},
            )
            scale_var = state
        block.insert_op(
            idx + 1,
            type="fake_dequantize_max_abs",
            inputs={"X": [quant_var.name], "Scale": [scale_var.name]},
            outputs={"Out": [dequant_var.name]},
            attrs={"max_range": max_range},
        )
        return 2

    # -- deployment --------------------------------------------------------
    def freeze_program(self, program, scope=None):
        """Strip the fake quant/dequant ops for deployment and snap every
        quantized WEIGHT in `scope` onto its int grid (round(w/s*Q)/Q*s),
        so the plain float program computes the quantized model exactly.
        Only inference programs may be frozen (the for_test clone taken
        before minimize, or a loaded inference model): removing the fake
        ops from a training graph would sever its gradient chain.
        Returns {weight name: scale} for int8 export tooling."""
        from paddle_tpu import framework
        from paddle_tpu.executor import global_scope

        scope = scope or global_scope()
        block = program.global_block()
        for op in block.ops:
            role = op.attrs.get(framework.OP_ROLE_ATTR_NAME, 0)
            if role & (framework.OpRole.Backward | framework.OpRole.Optimize):
                raise ValueError(
                    "freeze_program: program contains backward/optimizer "
                    "ops; freeze the clone(for_test=True) taken before "
                    "minimize instead")
        params = {p.name for p in block.all_parameters()}
        scales = {}

        # undo the input rewiring and drop the fake ops + their dead vars
        keep = []
        dead_vars = set()
        for op in block.ops:
            if op.type.startswith("fake_quantize") or \
                    op.type.startswith("fake_dequantize"):
                dead_vars.update(op.output_arg_names())
                continue
            for slot, names in list(op.inputs.items()):
                op.inputs[slot] = [
                    n[:-len(".dequantized")] if n.endswith(".dequantized")
                    else n
                    for n in names
                ]
            keep.append(op)
        block.ops[:] = keep

        # snap weights (identified by their now-dead .quantized twins)
        q = float((1 << (self.weight_bits - 1)) - 1)
        for name in sorted(params):
            if _quantized_name(name) not in dead_vars:
                continue
            val = scope.get_value(name)
            if val is None:
                continue
            w = np.asarray(val, np.float32)
            s = float(np.max(np.abs(w))) or 1e-8
            scope.set_value(name, np.round(w / s * q) / q * s)
            scales[name] = s

        for name in dead_vars:
            # running-scale STATE survives (it is real trained state a
            # later int8 exporter reads); pure wiring vars are dropped
            if name.endswith(".scale.state"):
                continue
            block.vars.pop(name, None)
        program._bump_version()
        return scales

    def convert_to_int8(self, program, scope=None, scales=None):
        """Rewrite a FROZEN inference program so its quantized weights are
        STORED int8 (reference: contrib/quantize/quantize_transpiler.py:348
        convert_to_int8): each weight ``w`` becomes an int8 persistable
        ``w.int8`` plus a per-tensor step ``w.int8_scale`` (= s/Q), and a
        ``dequantize_weight`` op rehydrates the float at the top of the
        block — ``save_inference_model`` then persists int8 tensors (4x
        smaller checkpoints + host->device transfers), and both serving
        engines (XLA and the C++ interpreter) dequantize on load. The
        dequantized floats are EXACTLY the grid values freeze_program
        snapped to, so outputs match the frozen model bit-for-float.

        ``scales``: the dict freeze_program returned; recomputed from the
        (already snapped) weights when omitted. Returns the list of
        converted weight names."""
        from paddle_tpu.executor import global_scope

        scope = scope or global_scope()
        block = program.global_block()
        q = float((1 << (self.weight_bits - 1)) - 1)
        if scales is None:
            # snapped weights: abs-max IS the original scale s
            scales = {}
            params = {p.name for p in block.all_parameters()}
            for op in block.ops:
                if op.type not in _QUANTIZABLE_OP_TYPES:
                    continue
                for name in op.input_arg_names():
                    if name in params and name not in scales:
                        val = scope.get_value(name)
                        if val is not None:
                            scales[name] = float(
                                np.max(np.abs(np.asarray(val)))) or 1e-8
        converted = []
        for name in sorted(scales):
            var = block.vars.get(name)
            val = scope.get_value(name)
            if var is None or val is None:
                continue
            s = scales[name]
            w = np.asarray(val, np.float32)
            i8 = np.clip(np.round(w / s * q), -q - 1, q).astype(np.int8)
            int8_name = name + ".int8"
            step_name = name + ".int8_scale"
            block.create_var(name=int8_name, shape=var.shape,
                             dtype="int8", persistable=True)
            block.create_var(name=step_name, shape=[1], dtype="float32",
                             persistable=True)
            # the float weight is now PRODUCED (by dequantize_weight),
            # not persisted: save_inference_model writes only the int8
            # twin + step
            var.persistable = False
            block.insert_op(
                0,
                type="dequantize_weight",
                inputs={"X": [int8_name], "Scale": [step_name]},
                outputs={"Out": [name]},
                attrs={},
            )
            scope.set_value(int8_name, i8)
            scope.set_value(step_name,
                            np.asarray([s / q], np.float32))
            scope.erase([name])
            converted.append(name)
        program._bump_version()
        return converted
