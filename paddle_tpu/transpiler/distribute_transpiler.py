"""DistributeTranspiler: single-process program -> distributed training.

Reference parity: ``python/paddle/fluid/transpiler/distribute_transpiler.py``
(:81 slice_variable, :240 transpile, get_trainer_program,
get_pserver_program, get_startup_program) — the reference rewrites the graph
into trainer programs (split/send/recv around the backward) and pserver
programs (listen_and_serv over per-grad optimize blocks).

TPU-first mapping: gradient exchange is NOT rewritten into RPC ops — the
trainer program stays whole and the data-parallel collectives come from
GSPMD when it runs under a mesh (``build_sharding_policy`` hands the
ParallelExecutor the plan; SURVEY.md §2.6 parallelism map). What this class
preserves from the reference is the *planning and structural* surface:
block-sliced parameter placement over endpoints (the sharded-pserver
capability), pserver-side optimize programs (runnable on the shard owner:
the host-offload path for huge embeddings), and the nccl2 mode that maps to
collective data parallel over the mesh.
"""

import math

from paddle_tpu import framework
from paddle_tpu.framework import (
    OP_ROLE_ATTR_NAME,
    OP_ROLE_VAR_ATTR_NAME,
    OpRole,
    Program,
)
from paddle_tpu.transpiler.ps_dispatcher import RoundRobin


class VarBlock(object):
    """One slice of a parameter: [offset, offset+size) of the flat var."""

    def __init__(self, varname, offset, size):
        self.varname = varname
        self.offset = offset
        self.size = size

    def __str__(self):
        return "%s:%d:%d" % (self.varname, self.offset, self.size)


def slice_variable(var_list, slice_count, min_block_size=8192):
    """Split vars into ~equal blocks, each >= min_block_size elements and
    aligned so a block holds whole rows (distribute_transpiler.py:81)."""
    blocks = []
    for var in var_list:
        numel = 1
        for d in var.shape or ():
            if int(d) > 0:
                numel *= int(d)
        split_count = slice_count
        max_pserver_count = int(math.floor(numel / float(min_block_size)))
        if max_pserver_count == 0:
            max_pserver_count = 1
        if max_pserver_count < slice_count:
            split_count = max_pserver_count
        block_size = int(math.ceil(numel / float(split_count)))

        if len(var.shape or ()) >= 2:
            # Align to whole rows.
            dim1 = 1
            for d in var.shape[1:]:
                dim1 *= int(d)
            remains = block_size % dim1
            if remains != 0:
                block_size += dim1 - remains
        split_count = int(math.ceil(numel / float(block_size)))
        for block_id in range(split_count):
            curr_size = min(block_size, numel - block_id * block_size)
            blocks.append(VarBlock(var.name, block_id * block_size,
                                   curr_size))
    return blocks


class DistributeTranspilerConfig(object):
    """slice_var_up: split big params into blocks over pservers;
    split_method: placement policy class; min_block_size: elements."""

    slice_var_up = True
    split_method = RoundRobin
    min_block_size = 8192

    def __init__(self, slice_var_up=True, split_method=None,
                 min_block_size=8192):
        self.slice_var_up = slice_var_up
        self.split_method = split_method or RoundRobin
        self.min_block_size = min_block_size


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._transpiled = False

    # -- planning ----------------------------------------------------------

    def _param_grad_pairs(self, program):
        """(param_name, grad_name) pairs from op_role_var on optimize ops
        (the reference reads the same attr, op_proto_maker OpRole)."""
        pairs = []
        seen = set()
        for op in program.global_block().ops:
            role = op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)
            rv = op.attrs.get(OP_ROLE_VAR_ATTR_NAME)
            if role == OpRole.Optimize and rv and len(rv) >= 2:
                p, g = rv[0], rv[1]
                if p not in seen:
                    seen.add(p)
                    pairs.append((p, g))
        return pairs

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  current_endpoint=""):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.sync_mode = sync_mode
        if not sync_mode:
            import warnings

            warnings.warn(
                "sync_mode=False is accepted for API parity but the "
                "transpiled program always runs SYNCHRONOUSLY: XLA arrays "
                "are immutable, so there is no racy-apply parameter store "
                "to run async SGD against — see docs/XLA_EXECUTION.md and "
                "docs/DISTRIBUTED_DESIGN.md", UserWarning, stacklevel=2)
        self.origin_program = program or framework.default_main_program()
        self.startup_program = (
            startup_program or framework.default_startup_program()
        )
        if isinstance(pservers, str):
            self.pserver_endpoints = [
                e for e in pservers.split(",") if e.strip()
            ]
        else:
            self.pserver_endpoints = list(pservers)
        self.current_endpoint = current_endpoint

        pairs = self._param_grad_pairs(self.origin_program)
        block = self.origin_program.global_block()
        params = [block._find_var_recursive(p) for p, _ in pairs]
        params = [p for p in params if p is not None]
        slice_count = (
            len(self.pserver_endpoints) if self.config.slice_var_up else 1
        )
        self.param_blocks = slice_variable(
            params, max(slice_count, 1), self.config.min_block_size
        )
        dispatcher = self.config.split_method(self.pserver_endpoints)
        eps = dispatcher.dispatch(self.param_blocks)
        self.param_block_map = {}  # endpoint -> [VarBlock]
        for blk, ep in zip(self.param_blocks, eps):
            self.param_block_map.setdefault(ep, []).append(blk)
        self.param_grad_map = dict(pairs)
        self._transpiled = True
        return self

    # -- outputs -----------------------------------------------------------

    def get_trainer_program(self):
        """The trainer keeps the whole graph: under the mesh, GSPMD inserts
        the gradient collectives the reference's send/recv ops performed."""
        assert self._transpiled, "call transpile() first"
        from paddle_tpu.analysis import verify_after_transpile

        verify_after_transpile(self.origin_program,
                               "DistributeTranspiler.get_trainer_program")
        return self.origin_program

    def build_sharding_policy(self, mesh, state_shapes=None,
                              sparse_tables=()):
        """The GSPMD execution of the plan: params that were block-sliced
        over pservers become dim-0-sharded state on the mesh (ZeRO-ish
        'reduce' strategy); sparse tables shard on the model axis (the
        distributed-lookup capability)."""
        from paddle_tpu.parallel.mesh import ShardingPolicy

        return ShardingPolicy(
            mesh,
            strategy="reduce" if len(self.pserver_endpoints) > 1
            else "all_reduce",
            state_shapes=state_shapes,
            model_sharded_vars=set(sparse_tables),
        )

    def get_pserver_program(self, endpoint):
        """A runnable optimize-only program for the params placed on
        ``endpoint``: for each owned param, the optimize ops from the origin
        program (listen_and_serv's per-grad block structure, flattened).
        Feeds: the grads; state: the owned params + optimizer accumulators.
        """
        assert self._transpiled, "call transpile() first"
        owned = {
            blk.varname for blk in self.param_block_map.get(endpoint, [])
        }
        pserver_prog = Program()
        pblock = pserver_prog.global_block()
        src_block = self.origin_program.global_block()

        needed_vars = set()
        ops_to_copy = []
        for op in src_block.ops:
            role = op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)
            rv = op.attrs.get(OP_ROLE_VAR_ATTR_NAME)
            # LR-schedule ops are replicated onto every pserver (the
            # reference clones lr-decay ops the same way) so copied
            # optimize ops never read a frozen/uninitialized rate.
            if role not in (OpRole.Optimize, OpRole.LRSched):
                continue
            if (role == OpRole.Optimize and rv and len(rv) >= 2
                    and rv[0] not in owned):
                continue
            ops_to_copy.append(op)
            needed_vars.update(op.input_arg_names())
            needed_vars.update(op.output_arg_names())
        for name in sorted(needed_vars):
            v = src_block._find_var_recursive(name)
            if v is None:
                continue
            nv = pblock.create_var(
                name=v.name, shape=v.shape, dtype=v.dtype, type=v.type,
                persistable=v.persistable,
            )
            nv.stop_gradient = v.stop_gradient
        for op in ops_to_copy:
            pblock.append_op(
                type=op.type,
                inputs={k: list(v) for k, v in op.inputs.items()},
                outputs={k: list(v) for k, v in op.outputs.items()},
                attrs=dict(op.attrs),
            )
        return pserver_prog

    def get_startup_program(self, endpoint, pserver_program=None):
        """Init ops for the params (+accumulators) owned by ``endpoint``."""
        assert self._transpiled, "call transpile() first"
        owned = {
            blk.varname for blk in self.param_block_map.get(endpoint, [])
        }
        if pserver_program is not None:
            owned = owned | {
                v for v in pserver_program.global_block().vars
            }
        startup = Program()
        sblock = startup.global_block()
        src = self.startup_program.global_block()
        for op in src.ops:
            outs = set(op.output_arg_names())
            if not outs & owned:
                continue
            for name in set(op.input_arg_names()) | outs:
                v = src._find_var_recursive(name)
                if v is not None and name not in sblock.vars:
                    sblock.create_var(
                        name=v.name, shape=v.shape, dtype=v.dtype,
                        type=v.type, persistable=v.persistable,
                    )
            sblock.append_op(
                type=op.type,
                inputs={k: list(v) for k, v in op.inputs.items()},
                outputs={k: list(v) for k, v in op.outputs.items()},
                attrs=dict(op.attrs),
            )
        return startup
