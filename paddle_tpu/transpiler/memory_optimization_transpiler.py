"""Memory-optimization transpiler.

Reference parity: ``transpiler/memory_optimization_transpiler.py`` (:112
ControlFlowGraph liveness, :263 memory_optimize var-reuse pool, :234
release_memory). The reference reuses dead variables' buffers during the
op-by-op interpreter walk. Under whole-program XLA that exact capability is
the compiler's (buffer assignment already reuses dead buffers), so the
TPU-native lever this transpiler controls is **gradient rematerialization**:
marking the program so every synthesized grad op recomputes its forward
values inside ``jax.checkpoint`` instead of letting XLA keep activations
live from the forward pass — trading FLOPs for peak HBM exactly like the
reference trades copies for reuse.

The liveness substrate itself now lives in ``analysis/liveness.py`` (the
ControlFlowGraph role, shared with the verifier and the metrics
registry); this transpiler consumes it instead of re-scanning the op
list, so the remat count excludes grad ops that are dead anyway.
"""

from paddle_tpu import framework

__all__ = ["memory_optimize", "release_memory"]


def memory_optimize(input_program=None, skip_opt_set=None, print_log=False,
                    level=0):
    """Enable activation rematerialization for the program's backward.

    skip_opt_set: var names whose producing ops must NOT be rematerialized
    (kept for API parity; matching grad ops keep stored activations).
    Returns the number of live grad ops that will rematerialize."""
    from paddle_tpu.analysis import liveness as _liveness

    program = input_program or framework.default_main_program()
    program._remat = True
    program._remat_skip = set(skip_opt_set or ())
    info = _liveness.analyze(program)
    count = 0
    dead_grad = 0
    for block in program.blocks:
        bl = info.block(block.idx)
        for i, op in enumerate(block.ops):
            if not op.type.endswith("_grad"):
                continue
            if bl.is_dead(i):
                dead_grad += 1
            else:
                count += 1
    if print_log:
        print(
            "memory_optimize: %d grad ops set to rematerialize "
            "(jax.checkpoint); %d dead grad ops excluded"
            % (count, dead_grad)
        )
    program._bump_version()
    return count


def release_memory(input_program=None, skip_opt_set=None):
    """The reference's eager-release pass; buffer lifetime is XLA's job
    under whole-program compilation — kept as an API-parity no-op."""
    return 0
