"""Parameter-block placement policies.

Reference parity: ``python/paddle/fluid/transpiler/ps_dispatcher.py``
(RoundRobin / HashName) — decides which endpoint (pserver in the reference;
mesh shard group here) owns each sliced parameter block.
"""


class PSDispatcher(object):
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for _v in varlist:
            out.append(self._eps[self._step])
            self._step = (self._step + 1) % len(self._eps)
        return out


class HashName(PSDispatcher):
    def dispatch(self, varlist):
        import zlib

        out = []
        for v in varlist:
            # VarBlock carries .varname; plain vars carry .name. crc32 is
            # process-stable (builtin str hash is salted per process, which
            # would give trainers and pservers conflicting placements).
            name = getattr(v, "varname", None) or v.name
            out.append(
                self._eps[zlib.crc32(name.encode()) % len(self._eps)]
            )
        return out
