"""AMP transpiler: enable bf16 mixed-precision compute on a Program.

Capability parity with the reference's fp16 transpiler
(``paddle/contrib/float16/float16_transpiler.py``), redesigned TPU-first
for *training*: instead of rewriting a serialized inference program with
explicit cast ops, the rewrite marks the Program and the Block->XLA
lowering applies dtype boundaries per op (core/amp.py white/black lists)
— master weights stay f32 in the Scope, conv/matmul run in bf16 on the
MXU, losses/optimizer updates compute in f32. Works for training AND
inference programs, and gradients inherit the precision of their forward
op automatically (vjp re-trace).
"""

from paddle_tpu.core import amp as amp_core

__all__ = ["rewrite_program_amp", "amp_guard", "AMP_WHITE_LIST",
           "AMP_BLACK_LIST"]

AMP_WHITE_LIST = amp_core.WHITE_LIST
AMP_BLACK_LIST = amp_core.BLACK_LIST


def rewrite_program_amp(program, amp_dtype="bfloat16"):
    """Mark ``program`` for mixed-precision lowering. Pass ``None`` to
    restore pure-f32 compute. Returns the program for chaining."""
    import jax.numpy as jnp

    if amp_dtype is not None:
        dt = jnp.dtype(amp_dtype)
        # fp16 would need a loss-scaling pass (its exponent range underflows
        # small grads); only bf16 (f32-range exponents) is sound without one.
        if dt != jnp.dtype(jnp.bfloat16):
            raise ValueError(
                "amp_dtype must be bfloat16 (float16 needs loss scaling, "
                "which this pass does not implement), got %r" % (amp_dtype,)
            )
        amp_dtype = dt.name
    program._amp_dtype = amp_dtype
    program._bump_version()
    return program


def amp_guard(program=None, amp_dtype="bfloat16"):
    """Context manager enabling AMP on ``program`` (default main program)
    for the duration of the block."""
    import contextlib

    from paddle_tpu import framework

    @contextlib.contextmanager
    def guard():
        prog = program or framework.default_main_program()
        prev = prog._amp_dtype
        rewrite_program_amp(prog, amp_dtype)
        try:
            yield prog
        finally:
            rewrite_program_amp(prog, prev)

    return guard()
