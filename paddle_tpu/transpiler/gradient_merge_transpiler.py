"""Gradient merge: accumulate K microbatch grads, apply the optimizer once.

Reference capability: multi_batch_merge_pass
(paddle/fluid/framework/ir/multi_batch_merge_pass.cc) — repeat a batch K
times, sum the grads, run one optimizer update for the merged batch.

TPU-first redesign: the reference clones the forward/backward subgraph K
times into one giant graph (K is baked into the executable and compile
time scales with it). Here the per-microbatch step function stays intact
and the optimizer apply becomes CONDITIONAL inside the same XLA program:

- every step, each grad is added into a persistable ``@GradientMerge``
  accumulator and a persistable step counter advances;
- every op that writes persistable state under an Optimize/LRSched role
  (param updates, moments, beta-pow scalings, LR schedule counters) has
  its writes gated by ``where_select(counter == K, new, old)``;
- on the boundary step the optimizer consumes the (optionally averaged)
  accumulator instead of the raw microbatch grad, and the accumulators
  reset to zero.

The gate is a select, not a branch, so XLA still compiles ONE static
program with no data-dependent control flow; the discarded update math on
non-boundary steps is a fused elementwise pass, negligible next to
forward+backward. Feeds stay per-microbatch (each ``exe.run`` is one
microbatch), which the graph-cloning design cannot do.

Semantics notes:
- ``avg=True`` divides the merged grad by K, so K microbatches of size
  B/K follow the same trajectory as one batch of size B (each microbatch
  loss being a mean over its samples). ``avg=False`` sums.
- Gradient clipping / regularization ops appended by ``minimize`` run on
  the raw per-microbatch grad BEFORE accumulation (same caveat as the
  reference pass, which merges whatever the optimizer was wired to read).
- LR schedule ops are gated too, so a decaying schedule advances once per
  merged step, matching the unmerged program step-for-step.
"""

from paddle_tpu import framework, initializer
from paddle_tpu.framework import OP_ROLE_ATTR_NAME, OpRole, VarType

__all__ = ["GradientMergeTranspiler", "rewrite_program_gradient_merge"]

_STEP_VAR = "@GradientMerge@.step"
_COND_VAR = "@GradientMerge@.cond"


def _is_gated_role(op):
    role = op.attrs.get(OP_ROLE_ATTR_NAME, OpRole.Forward)
    return role in (OpRole.Optimize, OpRole.LRSched)


class GradientMergeTranspiler(object):
    """Rewrite a training Program so optimizer state only advances every
    ``k_steps``-th run, with grads merged across the runs in between."""

    def transpile(self, program=None, startup_program=None, k_steps=1,
                  avg=True):
        program = program or framework.default_main_program()
        startup_program = (startup_program
                           or framework.default_startup_program())
        k_steps = int(k_steps)
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1, got %d" % k_steps)
        if k_steps == 1:
            return program  # no-op: every step is a boundary step
        if getattr(program, "_gradient_merge_k", None):
            # a second pass would double-increment the shared counter and
            # stack accumulators on accumulators — corrupt, so refuse
            raise ValueError(
                "program is already gradient-merge transpiled (k=%d)"
                % program._gradient_merge_k)
        block = program.global_block()

        gated_ops = [op for op in block.ops if _is_gated_role(op)]
        opt_ops = [op for op in gated_ops if op.input("Grad")
                   and op.input("Param")]
        if not opt_ops:
            raise ValueError(
                "gradient merge needs a program with optimizer ops "
                "(call optimizer.minimize before transpiling)")
        for op in opt_ops:
            gvar = block._find_var_recursive(op.input("Grad")[0])
            if gvar is not None and gvar.type == VarType.SELECTED_ROWS:
                raise ValueError(
                    "gradient merge does not support sparse "
                    "(SELECTED_ROWS) gradients: %r" % gvar.name)

        self._insert_counter(block, startup_program, k_steps)
        self._accumulate_grads(block, startup_program, opt_ops, k_steps, avg)
        self._gate_persistable_writes(block, gated_ops)
        self._reset_accumulators(block)
        program._gradient_merge_k = k_steps
        program._bump_version()
        from paddle_tpu.analysis import verify_after_transpile

        verify_after_transpile(program, "GradientMergeTranspiler")
        return program

    # -- pieces -------------------------------------------------------------
    @staticmethod
    def _startup_zero_var(startup_program, name, shape, dtype):
        sb = startup_program.global_block()
        if not sb.has_var(name):
            sv = sb.create_var(name=name, shape=shape, dtype=dtype,
                               persistable=True)
            initializer.ConstantInitializer(0.0)(sv, sb)

    def _insert_counter(self, block, startup_program, k_steps):
        """Prepend: step += 1; cond = (step == K); step = cond ? 0 : step.
        Prepending (not inserting at the first optimize op) makes the gate
        available to LR-schedule ops, which sit early in the block."""
        attrs = {OP_ROLE_ATTR_NAME: OpRole.Optimize}
        block.create_var(name=_STEP_VAR, shape=[1], dtype="int32",
                         persistable=True)
        block.create_var(name=_COND_VAR, shape=[1], dtype="bool")
        k_var = block.create_var(name="@GradientMerge@.k", shape=[1],
                                 dtype="int32")
        zero = block.create_var(name="@GradientMerge@.zero", shape=[1],
                                dtype="int32")
        self._startup_zero_var(startup_program, _STEP_VAR, [1], "int32")
        ops = [
            ("fill_constant", {}, {"Out": [k_var.name]},
             dict(attrs, shape=[1], dtype="int32", value=float(k_steps))),
            ("fill_constant", {}, {"Out": [zero.name]},
             dict(attrs, shape=[1], dtype="int32", value=0.0)),
            ("increment", {"X": [_STEP_VAR]}, {"Out": [_STEP_VAR]},
             dict(attrs, step=1.0)),
            ("equal", {"X": [_STEP_VAR], "Y": [k_var.name]},
             {"Out": [_COND_VAR]}, dict(attrs)),
            ("where_select",
             {"Cond": [_COND_VAR], "X": [zero.name], "Y": [_STEP_VAR]},
             {"Out": [_STEP_VAR]}, dict(attrs)),
        ]
        for i, (tp, ins, outs, at) in enumerate(ops):
            block.insert_op(i, type=tp, inputs=ins, outputs=outs, attrs=at)

    def _accumulate_grads(self, block, startup_program, opt_ops, k_steps,
                          avg):
        """acc += grad right before each optimize op; point its Grad input
        at the (averaged) accumulator."""
        attrs = {OP_ROLE_ATTR_NAME: OpRole.Optimize}
        self._acc_names = []
        done = set()
        for op in opt_ops:
            g_name = op.input("Grad")[0]
            gvar = block._find_var_recursive(g_name)
            acc_name = g_name + "@GradientMerge"
            read_name = acc_name + "@AVG" if avg else acc_name
            if g_name not in done:
                done.add(g_name)
                self._acc_names.append(acc_name)
                block.create_var(name=acc_name, shape=gvar.shape,
                                 dtype=gvar.dtype, persistable=True)
                self._startup_zero_var(startup_program, acc_name,
                                       list(gvar.shape or [1]), gvar.dtype)
                idx = block.ops.index(op)
                block.insert_op(
                    idx, type="elementwise_add",
                    inputs={"X": [acc_name], "Y": [g_name]},
                    outputs={"Out": [acc_name]}, attrs=dict(attrs))
                if avg:
                    block.create_var(name=read_name, shape=gvar.shape,
                                     dtype=gvar.dtype)
                    block.insert_op(
                        idx + 1, type="scale",
                        inputs={"X": [acc_name]},
                        outputs={"Out": [read_name]},
                        attrs=dict(attrs, scale=1.0 / k_steps))
            op.inputs["Grad"] = [read_name]

    def _gate_persistable_writes(self, block, gated_ops):
        """For each Optimize/LRSched op output bound to a persistable var,
        reroute the write to a temp and select (cond ? new : old) back into
        the var, so state only advances on boundary steps."""
        attrs = {OP_ROLE_ATTR_NAME: OpRole.Optimize}
        for op_seq, op in enumerate(gated_ops):
            selects = []
            for slot, names in op.outputs.items():
                for j, name in enumerate(names):
                    var = block._find_var_recursive(name)
                    if var is None or not var.persistable:
                        continue
                    tmp = block.create_var(
                        name="%s@GM_NEW.%d" % (name, op_seq),
                        shape=var.shape, dtype=var.dtype)
                    names[j] = tmp.name
                    selects.append((tmp.name, name))
            idx = block.ops.index(op) + 1
            for tmp_name, name in selects:
                block.insert_op(
                    idx, type="where_select",
                    inputs={"Cond": [_COND_VAR], "X": [tmp_name],
                            "Y": [name]},
                    outputs={"Out": [name]}, attrs=dict(attrs))
                idx += 1

    def _reset_accumulators(self, block):
        """Append: acc = cond ? zeros : acc, for every accumulator."""
        attrs = {OP_ROLE_ATTR_NAME: OpRole.Optimize}
        for acc_name in self._acc_names:
            zero_name = acc_name + "@ZERO"
            var = block.var(acc_name)
            block.create_var(name=zero_name, shape=var.shape,
                             dtype=var.dtype)
            block.append_op(
                type="fill_zeros_like", inputs={"X": [acc_name]},
                outputs={"Out": [zero_name]}, attrs=dict(attrs))
            block.append_op(
                type="where_select",
                inputs={"Cond": [_COND_VAR], "X": [zero_name],
                        "Y": [acc_name]},
                outputs={"Out": [acc_name]}, attrs=dict(attrs))


def rewrite_program_gradient_merge(program=None, startup_program=None,
                                   k_steps=1, avg=True):
    """Functional wrapper over :class:`GradientMergeTranspiler`."""
    return GradientMergeTranspiler().transpile(
        program, startup_program, k_steps=k_steps, avg=avg)
