"""Inference transpiler: fold batch_norm into the preceding conv/fc for a
pre-optimized deploy program.

Reference parity: python/paddle/fluid/transpiler/inference_transpiler.py
(fuse_batch_norm). The capability is to *serialize* an already-optimized
program — at runtime XLA would fuse these anyway, but a folded program (a)
ships fewer parameters, (b) runs as-is on the native C++ interpreter, and
(c) matches the reference deployment flow (save_inference_model after
transpile).

Given ``conv2d -> (elementwise_add bias ->) batch_norm`` the BN affine is
folded into the conv filter and bias:

    a = scale / sqrt(variance + eps)
    W' = W * a[:, None, None, None]
    b' = (b - mean) * a + bn_bias

The batch_norm op and its now-unused parameters are removed from the
program, and downstream readers of the BN output are rewired to the conv
(or bias-add) output. Values are updated in the scope in place.
"""

import numpy as np

__all__ = ["InferenceTranspiler"]


class InferenceTranspiler(object):
    def transpile(self, program, scope=None, place=None):
        """Fold conv+bn pairs in ``program`` (in place), updating parameter
        values in ``scope`` (defaults to the global scope)."""
        if scope is None:
            from paddle_tpu.executor import global_scope

            scope = global_scope()
        block = program.global_block()

        i = 0
        while i < len(block.ops) - 1:
            op = block.ops[i]
            if op.type != "conv2d":
                i += 1
                continue
            conv_out = op.output("Output")[0]
            j = i + 1
            bias_op = None
            nxt = block.ops[j]
            if (
                nxt.type == "elementwise_add"
                and nxt.input("X")
                and nxt.input("X")[0] == conv_out
                and j + 1 < len(block.ops)
                and self._is_parameter(block, nxt.input("Y"))
            ):
                # only a parameter Y is a bias; a residual/skip add (Y is an
                # activation) must not be folded into
                bias_op = nxt
                j += 1
                nxt = block.ops[j]
            if nxt.type != "batch_norm":
                i += 1
                continue
            bn_in = nxt.input("X")[0]
            expect = bias_op.output("Out")[0] if bias_op else conv_out
            if bn_in != expect:
                i += 1
                continue
            self._fold(block, scope, op, bias_op, nxt, j)
            i += 1
        program._bump_version()
        from paddle_tpu.analysis import verify_after_transpile

        verify_after_transpile(program, "InferenceTranspiler")
        return program

    @staticmethod
    def _is_parameter(block, names):
        from paddle_tpu.framework import Parameter

        if not names:
            return False
        var = block.vars.get(names[0])
        return isinstance(var, Parameter)

    def _fold(self, block, scope, conv_op, bias_op, bn_op, bn_idx):
        eps = bn_op.attr("epsilon") if bn_op.has_attr("epsilon") else 1e-5
        w_name = conv_op.input("Filter")[0]
        scale = np.asarray(scope.get_value(bn_op.input("Scale")[0]))
        bn_bias = np.asarray(scope.get_value(bn_op.input("Bias")[0]))
        mean = np.asarray(scope.get_value(bn_op.input("Mean")[0]))
        var = np.asarray(scope.get_value(bn_op.input("Variance")[0]))
        a = scale / np.sqrt(var + eps)

        w = np.asarray(scope.get_value(w_name))
        scope.set_value(w_name, (w * a[:, None, None, None]).astype(w.dtype))

        if bias_op is not None:
            b_name = bias_op.input("Y")[0]
            b = np.asarray(scope.get_value(b_name)).reshape(-1)
            new_b = ((b - mean) * a + bn_bias).astype(b.dtype)
            scope.set_value(b_name, new_b.reshape(np.asarray(
                scope.get_value(b_name)).shape))
            out_name = bias_op.output("Out")[0]
        else:
            # fold the BN shift into a fresh bias parameter + add op
            b_name = w_name + ".bn_fused_bias"
            new_b = ((0.0 - mean) * a + bn_bias).astype(w.dtype)
            block.create_parameter(
                name=b_name, shape=[int(new_b.shape[0])], dtype=str(w.dtype)
            )
            scope.set_value(b_name, new_b)
            conv_out = conv_op.output("Output")[0]
            out_name = bn_op.output("Y")[0]
            block.insert_op(
                bn_idx,
                "elementwise_add",
                inputs={"X": [conv_out], "Y": [b_name]},
                outputs={"Out": [out_name]},
                attrs={"axis": 1},
            )
            bn_idx += 1

        bn_out = bn_op.output("Y")[0]
        # drop the BN op and point its readers at the folded output
        block.remove_op(bn_idx)
        if bias_op is not None and bn_out != out_name:
            for later in block.ops:
                for slot, names in list(later.inputs.items()):
                    later.inputs[slot] = [
                        out_name if n == bn_out else n for n in names
                    ]
        # remove BN params from the program so serialization skips them
        for slot in ("Scale", "Bias", "Mean", "Variance"):
            name = bn_op.input(slot)[0]
            block.vars.pop(name, None)
