"""Weight-decay regularizers appended as grad-rewrite ops
(python/paddle/fluid/regularizer.py parity)."""

from paddle_tpu import framework

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [param.name]},
            outputs={"Out": [decay.name]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="sign", inputs={"X": [param.name]}, outputs={"Out": [sign.name]}
        )
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(
            type="scale",
            inputs={"X": [sign.name]},
            outputs={"Out": [decay.name]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """grad += decay(param); per-param regularizer overrides global one
    (regularizer.py append_regularization_ops parity)."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularization_term = None
        block = grad.block
        with block.program._optimized_guard([param, grad]):
            if getattr(param, "regularizer", None) is not None:
                regularization_term = param.regularizer(param, grad, block)
            elif regularization is not None:
                regularization_term = regularization(param, grad, block)
            if regularization_term is None:
                params_and_grads.append((param, grad))
                continue
            new_grad = block.create_var(
                name=grad.name + "@REGULARIZED",
                dtype=param.dtype,
                shape=param.shape,
            )
            block.append_op(
                type="sum",
                inputs={"X": [grad.name, regularization_term.name]},
                outputs={"Out": [new_grad.name]},
            )
        params_and_grads.append((param, new_grad))
    return params_and_grads


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
