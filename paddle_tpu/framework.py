"""The declarative Program graph IR, built from Python.

Reference parity: ``python/paddle/fluid/framework.py`` (Program:1404,
Block:920, Operator:494, Variable:204) and the C++ desc layer
(``paddle/fluid/framework/program_desc.h:30``, ``block_desc.h:38``,
``op_desc.h:29``, ``var_desc.h:58``). Programs here are the unit the
Executor compiles whole to XLA; ops carry schemas from the op registry and
shape inference runs through ``jax.eval_shape`` on each op's lowering rule —
one source of truth for shapes instead of hand-written InferShape per op.
"""

import contextlib
import copy

import numpy as np

from paddle_tpu.core import op_registry
from paddle_tpu.core.types import VarType, canonical_dtype, CPUPlace, TPUPlace

# Sentinel used to stand in for the -1 (dynamic batch) dimension during
# build-time shape inference; output dims equal to it map back to -1.
_DYN_SENTINEL = 557

# OpRole attr (op_proto_maker.cc parity) — transpilers classify ops by role.
OP_ROLE_ATTR_NAME = "op_role"
OP_ROLE_VAR_ATTR_NAME = "op_role_var"


class OpRole(object):
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256


class Variable(object):
    """A typed symbolic value in a Block (framework.py:204 parity)."""

    def __init__(
        self,
        block,
        name,
        shape=None,
        dtype="float32",
        lod_level=0,
        persistable=False,
        stop_gradient=False,
        type=VarType.LOD_TENSOR,
        is_data=False,
        initializer=None,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype) if type == VarType.LOD_TENSOR else dtype
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.type = type
        self.is_data = is_data
        self.initializer = initializer
        self.op = None  # producing op (set by append_op)

    @property
    def ndim(self):
        return None if self.shape is None else len(self.shape)

    def astype(self, dtype):
        from paddle_tpu.layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__

    # Operator sugar so variables compose like arrays in user scripts.
    def _binary(self, other, op, reverse=False):
        from paddle_tpu.layers import math_ops

        if reverse:
            return math_ops.elementwise_binary_reversed(op, self, other)
        return math_ops.elementwise_binary(op, self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __pow__(self, other):
        return self._binary(other, "elementwise_pow")

    def __rpow__(self, other):
        return self._binary(other, "elementwise_pow", reverse=True)

    def __neg__(self):
        from paddle_tpu.layers import nn

        return nn.scale(self, scale=-1.0)


class Parameter(Variable):
    """A trainable persistable Variable (framework.py Parameter parity)."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super(Parameter, self).__init__(
            block, name, shape=shape, dtype=dtype, persistable=True, **kwargs
        )
        self.stop_gradient = not self.trainable


class Operator(object):
    """One op instance in a Block (framework.py:494 / op_desc.h:29 parity).

    inputs/outputs: dict slot -> list of var names. attrs: plain dict.
    """

    def __init__(self, block, type, inputs, outputs, attrs=None):
        op_registry.get_op_def(type)  # validate registration
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        prog = block.program
        self.attrs.setdefault(OP_ROLE_ATTR_NAME, prog._op_role)
        if prog._op_role_var and OP_ROLE_VAR_ATTR_NAME not in self.attrs:
            self.attrs[OP_ROLE_VAR_ATTR_NAME] = list(prog._op_role_var)
        if "__rng_id__" not in self.attrs:
            self.attrs["__rng_id__"] = prog._next_rng_id()

    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name):
        return self.attrs[name]

    def set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (
            self.type,
            ", ".join("%s=%s" % kv for kv in self.inputs.items()),
            ", ".join("%s=%s" % kv for kv in self.outputs.items()),
        )


class Block(object):
    """A straight-line list of ops + a var symbol table (framework.py:920)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}  # name -> Variable
        self.ops = []
        self.forward_block_idx = -1

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise KeyError("var %r not in block %d" % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        block = self
        while block is not None:
            v = block.vars.get(name)
            if v is not None:
                return v
            block = block.parent_block
        return None

    def has_var(self, name):
        return name in self.vars

    def has_var_recursive(self, name):
        return self._find_var_recursive(name) is not None

    def create_var(self, name=None, **kwargs):
        from paddle_tpu import unique_name

        if name is None:
            name = unique_name.generate("tmp")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        self.program._bump_version()
        return v

    def create_parameter(self, name, shape, dtype, **kwargs):
        # Parameters always live in the global (root) block, as in Fluid.
        global_block = self.program.global_block()
        if name in global_block.vars:
            return global_block.vars[name]
        p = Parameter(global_block, name, shape, dtype, **kwargs)
        global_block.vars[name] = p
        self.program._bump_version()
        return p

    def rename_var(self, old, new):
        v = self.vars.pop(old)
        v.name = new
        self.vars[new] = v
        for op in self.ops:
            for names in list(op.inputs.values()) + list(op.outputs.values()):
                for i, n in enumerate(names):
                    if n == old:
                        names[i] = new
        self.program._bump_version()
        return v

    def append_op(self, type, inputs=None, outputs=None, attrs=None, infer_shape=True):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        if infer_shape:
            try:
                _infer_op_shapes(self, op)
            except Exception:
                # Shape inference is best-effort at build time; execution
                # re-derives exact shapes from concrete feeds. Record the
                # deferral so infer_deferred_shapes can retry once feed
                # shapes are known (reader pipelines declare shapes late)
                # instead of leaving Variable.shape=None forever.
                self.program._defer_shape_inference(self.idx, op)
        else:
            self.program._defer_shape_inference(self.idx, op)
        for name in op.output_arg_names():
            v = self.vars.get(name)
            if v is not None and v.op is None:
                v.op = op
        self.program._bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        try:
            _infer_op_shapes(self, op)
        except Exception:
            self.program._defer_shape_inference(self.idx, op)
        self.program._bump_version()
        return op

    def insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def remove_op(self, index):
        self.ops.pop(index)
        self.program._bump_version()

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def iter_parameters(self):
        return iter(self.all_parameters())


class Program(object):
    """A list of Blocks; block 0 is global (framework.py:1404 parity).

    ``_version`` invalidates the Executor's executable cache on mutation
    (feed/fetch/transpiler graph surgery), mirroring the reference's
    program-cache keyed Executor (executor.py use_program_cache).
    """

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._rng_counter = 0
        self._is_test = False
        # Mixed-precision compute dtype (core/amp.py); None = pure f32.
        self._amp_dtype = None
        self._op_role = OpRole.Forward
        self._op_role_var = []
        # (block idx, op) pairs whose build-time shape inference was
        # skipped or failed; infer_deferred_shapes retries them.
        self._deferred_infer = []

    # -- structure ----------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent_idx=parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def _defer_shape_inference(self, block_idx, op):
        # getattr: Programs deserialized from old pickles predate the slot
        if not hasattr(self, "_deferred_infer"):
            self._deferred_infer = []
        self._deferred_infer.append((block_idx, op))

    def infer_deferred_shapes(self, feed_shapes=None):
        """Retry shape inference for ops deferred at append time.

        ``append_op(infer_shape=False)`` and build-time inference
        failures (inputs whose shapes were unknown when the op was
        appended — reader pipelines, decoupled graph surgery) leave
        ``Variable.shape=None``. Once feed shapes are known, this re-runs
        the registry inference in append order: ``feed_shapes`` maps var
        name -> shape for data vars still missing one. Ops that succeed
        leave the deferred list; returns ``[(block_idx, op, error)]`` for
        those that still fail (the verifier turns these into V011
        diagnostics instead of letting them crash the XLA trace)."""
        pending = getattr(self, "_deferred_infer", None)
        if not pending:
            return []
        # Memoized per (version, feed shapes): ops that keep failing must
        # not re-run eval_shape on every Executor.run of a steady-state
        # program — only when the graph or the feed signature changes.
        memo_key = (self._version, tuple(sorted(
            (n, tuple(int(d) for d in s))
            for n, s in (feed_shapes or {}).items())))
        memo = getattr(self, "_deferred_infer_memo", None)
        if memo is not None and memo[0] == memo_key:
            return memo[1]
        for name, shape in (feed_shapes or {}).items():
            v = self.global_block()._find_var_recursive(name)
            if v is not None and v.shape is None:
                v.shape = tuple(int(d) for d in shape)
                self._bump_version()
        failures, remaining, resolved = [], [], False
        for block_idx, op in pending:
            block = self.blocks[block_idx] if block_idx < len(
                self.blocks) else None
            if block is None or not any(o is op for o in block.ops):
                continue  # op was pruned/removed since the deferral
            try:
                _infer_op_shapes(block, op)
                resolved = True
            except Exception as e:
                failures.append((block_idx, op, str(e)))
                remaining.append((block_idx, op))
        self._deferred_infer = remaining
        if resolved:
            self._bump_version()
        self._deferred_infer_memo = (
            (self._version, memo_key[1]), failures)
        return failures

    def verify(self, level="error", fetch_names=None, feed_shapes=None,
               feed_names=None, suppress=()):
        """Run the structural verifier (analysis/verify.py) over this
        program. Raises ``analysis.ProgramVerifyError`` when any
        diagnostic sits at or above ``level`` (pass level=None to only
        collect); returns the full diagnostics list otherwise."""
        from paddle_tpu.analysis import check_program

        return check_program(
            self, level=level, fetch_names=fetch_names,
            feed_shapes=feed_shapes, feed_names=feed_names,
            suppress=suppress)

    def memory_plan(self, feed_shapes=None, fetch_names=None,
                    shard_factors=None):
        """Predict this program's per-step HBM high-water mark
        (observability/memory.py): walks the liveness analysis with byte
        accounting and returns a :class:`observability.memory.MemoryPlan`
        — peak bytes, the op where the peak occurs, and the top live
        tensors there. ``feed_shapes`` (name -> shape) resolves dynamic
        (-1) dims; ``fetch_names`` anchor the live-out set.
        ``shard_factors`` ({var -> ways split}, e.g. from
        ``parallel.sharding.plan_shard_factors``) divides those vars'
        bytes so the predicted peak is PER-DEVICE residency under a
        sharding plan, not logical bytes."""
        from paddle_tpu.observability import memory as _memory

        return _memory.plan_program(
            self, feed_shapes=feed_shapes,
            fetch_names=tuple(fetch_names or ()),
            shard_factors=shard_factors)

    def derive_sharding(self, mesh_axes, overrides=None, feed_shapes=None,
                        **kwargs):
        """Derive a GSPMD :class:`parallel.sharding.ShardingPlan` for this
        program over ``mesh_axes`` (a ``jax.sharding.Mesh`` or an
        ``{axis: size}`` dict with the ``data``/``fsdp``/``tp`` axis
        vocabulary): walks the op graph, annotates every var's
        ``partition_spec`` (canonical rules for matmul/conv/embedding/
        norm, propagation through elementwise/reshape ops, explicit
        reshard points on conflicts). ``overrides`` (the old hand-written
        ``tp_layout`` surface) take precedence and are validated by
        analysis rule S001 at transpile time."""
        from paddle_tpu.parallel.sharding import derive_sharding

        return derive_sharding(self, mesh_axes, overrides=overrides,
                               feed_shapes=feed_shapes, **kwargs)

    def _next_rng_id(self):
        self._rng_counter += 1
        return self._rng_counter

    # -- op role guard (transpiler classification) --------------------------
    @contextlib.contextmanager
    def _optimized_guard(self, param_and_grads):
        prev_role, prev_var = self._op_role, self._op_role_var
        self._op_role = OpRole.Optimize
        self._op_role_var = [
            v.name if isinstance(v, Variable) else v for v in param_and_grads
        ]
        try:
            yield
        finally:
            self._op_role, self._op_role_var = prev_role, prev_var

    @contextlib.contextmanager
    def _lr_schedule_guard(self):
        prev = self._op_role
        self._op_role = OpRole.LRSched
        try:
            yield
        finally:
            self._op_role = prev

    # -- cloning / pruning ---------------------------------------------------
    def clone(self, for_test=False):
        """Deep copy; for_test flips is_test attrs (dropout/BN inference
        behavior) as in framework.py Program.clone."""
        p = copy.deepcopy(self)
        if for_test:
            p._is_test = True
            for block in p.blocks:
                for op in block.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
        p._bump_version()
        return p

    def list_vars(self):
        for block in self.blocks:
            for v in block.vars.values():
                yield v

    def __repr__(self):
        lines = []
        for block in self.blocks:
            lines.append("-- block %d (parent %d) --" % (block.idx, block.parent_idx))
            for v in block.vars.values():
                lines.append("  " + repr(v))
            for op in block.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = __repr__


# ---------------------------------------------------------------------------
# Shape inference through jax.eval_shape on the lowering rule
# ---------------------------------------------------------------------------


def _infer_op_shapes(block, op):
    opdef = op_registry.get_op_def(op.type)
    if opdef.infer_shape is not None:
        opdef.infer_shape(block, op)
        return
    import jax

    ins_structs = {}
    had_dynamic = False
    for slot in opdef.input_slots():
        arrs = []
        for name in op.input(slot):
            v = block._find_var_recursive(name)
            if v is None or v.shape is None:
                raise ValueError("unknown shape for input %s" % name)
            shape = []
            for d in v.shape:
                if d < 0:
                    shape.append(_DYN_SENTINEL)
                    had_dynamic = True
                else:
                    shape.append(d)
            arrs.append(jax.ShapeDtypeStruct(tuple(shape), np.dtype(_np_name(v.dtype))))
        # Match the executor's lower_op contract: absent optional slots are
        # omitted from ins entirely (not passed as empty lists).
        if arrs:
            ins_structs[slot] = arrs

    def f(ins):
        import jax.random as jrandom

        from paddle_tpu.core.lowering import BlockLowerer

        ctx = op_registry.LowerContext(
            op,
            rng=lambda: jrandom.PRNGKey(0),
            is_test=False,
            # Sub-block mega-ops (recurrent/cond/while) lower their nested
            # blocks through this — required for their shape inference too.
            block_lowerer=BlockLowerer(block.program, block.idx),
        )
        return op_registry.normalize_outputs(opdef, opdef.lower(ctx, ins, op.attrs))

    out = jax.eval_shape(f, ins_structs)
    for slot, structs in out.items():
        names = op.output(slot)
        for name, s in zip(names, structs):
            v = block._find_var_recursive(name)
            if v is None:
                continue
            # The sentinel is prime, so any output dim it *multiplies into*
            # (reshape/flatten merging batch with feature dims) is a
            # multiple of it — map those back to -1 too, not just exact hits.
            shape = tuple(
                -1
                if (had_dynamic and d != 0 and d % _DYN_SENTINEL == 0)
                else int(d)
                for d in s.shape
            )
            v.shape = shape
            v.dtype = canonical_dtype(s.dtype)


def _np_name(dtype):
    name = canonical_dtype(dtype)
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return name


# ---------------------------------------------------------------------------
# Default programs + guards (framework.py:2061-2129 parity)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    prev, _main_program = _main_program, program
    return prev


def switch_startup_program(program):
    global _startup_program
    prev, _startup_program = _startup_program, program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


def _current_op_role():
    return default_main_program()._op_role


def grad_var_name(name):
    return name + "@GRAD"


def cpu_places(device_count=None):
    import jax

    n = device_count or max(1, len([d for d in jax.devices() if d.platform == "cpu"]))
    return [CPUPlace(i) for i in range(n)]


def tpu_places(device_ids=None):
    import jax

    if device_ids is None:
        non_cpu = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
        device_ids = range(len(non_cpu))
    return [TPUPlace(i) for i in device_ids]
