"""ParallelExecutor: multi-device (and multi-host) training via GSPMD.

Reference parity: python/paddle/fluid/parallel_executor.py +
paddle/fluid/framework/parallel_executor.cc:58. The reference builds
per-device SSA graphs with inserted NCCL allreduce ops and runs them with a
threaded dataflow scheduler; here the SAME program is jit-compiled once
over a jax.sharding.Mesh with a ShardingPolicy — XLA emits the fused
per-device program plus ICI/DCN collectives, and runs it on all devices
(no host-side scheduler needed).

BuildStrategy.ReduceStrategy maps to the policy:
  AllReduce -> replicated params (grad allreduce), build_strategy.h:55
  Reduce    -> fsdp over the DERIVED sharding plan: the sharding
               transpiler (parallel/sharding.derive_sharding) walks the
               op graph and picks a per-var PartitionSpec over the
               (data, fsdp, tp) mesh — reduce-scatter + all-gather,
               ZeRO-ish — instead of the old blanket dim-0 sharding.
               Hand-written ``sharding_overrides`` naming the legacy
               "model"/"pipe" axes keep the legacy blanket policy.
num_trainers/trainer_id (NCCL2 multi-node) -> jax.distributed processes.

Tensor parallelism needs NO hand-written layout: pass ``tp=`` (and/or
``fsdp=``) and the transpiler derives Megatron column/row splits from
the graph; ``sharding_overrides`` remain an *override* on top of the
derived plan, validated by analysis rule S001 at transpile time.
"""

import threading
import time
from collections import OrderedDict

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu import framework
from paddle_tpu import profiler as _profiler
from paddle_tpu.core import exec_cache
from paddle_tpu.observability import blackbox as _blackbox
from paddle_tpu.observability import explain as _explain
from paddle_tpu.observability import lock_witness
from paddle_tpu.observability import memory as _memory
from paddle_tpu.observability import step_profiler as _stepprof
from paddle_tpu.observability import telemetry as _telemetry
from paddle_tpu.resilience import chaos as _chaos
from paddle_tpu.resilience import retry as _retry
from paddle_tpu.core.fingerprint import (
    executable_key,
    program_fingerprint,
    trace_flags_key,
)
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.core.lowering import CompiledProgram
from paddle_tpu.executor import global_scope
from paddle_tpu.parallel.mesh import ShardingPolicy, build_mesh


# Process-global GSPMD executable registry (the executor.py shared-
# registry idiom, mesh-aware): content-addressed keys extended with the
# mesh's device identity and every policy input, so a ParallelExecutor
# REBUILT over the same devices — the elastic runtime tears one down and
# rebuilds per membership generation — reuses the compiled sharded
# executable instead of paying a fresh XLA compile. A fleet that
# reshapes 2 -> 1 -> 2 compiles twice, not three times.
_shared_compiled = OrderedDict()
_shared_lock = lock_witness.make_lock("parallel_executor.shared_cache")
_SHARED_CAP = 32


class ExecutionStrategy(object):
    """execution_strategy.h:21 parity (scheduler knobs are no-ops under XLA,
    kept for API compat)."""

    class ExecutorType(object):
        Default = 0
        Experimental = 1

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100
        self.use_experimental_executor = False


class BuildStrategy(object):
    """build_strategy.h:34 parity."""

    class ReduceStrategy(object):
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy(object):
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = (
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        )
        self.debug_graphviz_path = ""
        self.enable_data_balance = False
        self.fuse_elewise_add_act_ops = False


def _names_legacy_axes(sharding_overrides):
    """True when any hand-written override references a legacy-mesh axis
    ("model"/"pipe", or "data" — which the Reduce planning mesh shrinks
    to size 1, so an old `('data', …)` layout would silently stop
    sharding there). Those layouts predate the planning (data, fsdp, tp)
    vocabulary and keep the legacy blanket policy. Malformed specs
    return False so the planning path's S001 validation names the actual
    problem."""
    from paddle_tpu.analysis.shard_check import spec_axes

    for spec in (sharding_overrides or {}).values():
        try:
            if set(spec_axes(spec)) & {"model", "pipe", "data"}:
                return True
        except ValueError:
            pass
    return False


def _warn_noop_strategy_knobs(build_strategy, exec_strategy):
    """Tell the user, once, when they set a knob the XLA execution model
    makes meaningless (docs/XLA_EXECUTION.md has the per-knob rationale)."""
    import warnings

    noop = []
    bs_defaults = BuildStrategy()
    # unlike reduce_strategy (honored in _shard_grad_outputs), these two
    # never reach the lowering — changing them would silently change
    # nothing, so say so
    for f in ("gradient_scale_strategy", "enable_data_balance"):
        if getattr(build_strategy, f, None) != getattr(bs_defaults, f):
            noop.append("BuildStrategy.%s" % f)
    defaults = ExecutionStrategy()
    for f in ("num_threads", "allow_op_delay", "num_iteration_per_drop_scope",
              "use_experimental_executor"):
        if getattr(exec_strategy, f, None) != getattr(defaults, f):
            noop.append("ExecutionStrategy.%s" % f)
    if noop:
        warnings.warn(
            "%s have no effect: the whole program compiles to one XLA "
            "executable, which owns scheduling and elementwise fusion — "
            "see docs/XLA_EXECUTION.md" % ", ".join(noop),
            UserWarning, stacklevel=3)


class ParallelExecutor(object):
    def __init__(
        self,
        use_cuda=False,
        loss_name=None,
        main_program=None,
        share_vars_from=None,
        exec_strategy=None,
        build_strategy=None,
        num_trainers=1,
        trainer_id=0,
        scope=None,
        use_tpu=True,
        num_devices=None,
        model_sharded_vars=None,
        sharding_overrides=None,
        pipeline_stages=None,
        pipeline_microbatches=None,
        fsdp=None,
        tp=None,
    ):
        self._program = main_program or framework.default_main_program()
        self._scope = scope or global_scope()
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        _warn_noop_strategy_knobs(self._build_strategy, self._exec_strategy)
        if getattr(self._build_strategy, "fuse_elewise_add_act_ops", False):
            # fuse_elewise_add_act_pass.cc role: collapse add+act (and the
            # backward twin) into fused ops before compiling the program
            from paddle_tpu.core.passes import apply_pass

            self._program = apply_pass(self._program, "fuse_elewise_add_act")
        self._loss_name = loss_name
        self._cache = {}
        self._run_counter = 0
        self._base_seed = np.random.randint(0, 2**31 - 1)

        # Multi-trainer (NCCL2-mode parity): each trainer is one
        # jax.distributed process; the mesh spans the GLOBAL device list and
        # XLA's collectives cross hosts the way gen_nccl_id-bootstrapped
        # ncclAllReduce did (gen_nccl_id_op.cc:31, nccl_helper.h:103-120).
        self._num_trainers = int(num_trainers)
        self._trainer_id = int(trainer_id)
        if self._num_trainers > 1:
            if jax.process_count() != self._num_trainers:
                raise RuntimeError(
                    "num_trainers=%d but jax.process_count()=%d — call "
                    "paddle_tpu.parallel.init_distributed(coordinator, "
                    "num_processes, process_id) before ParallelExecutor"
                    % (self._num_trainers, jax.process_count())
                )
            if jax.process_index() != self._trainer_id:
                raise RuntimeError(
                    "trainer_id=%d does not match jax.process_index()=%d"
                    % (self._trainer_id, jax.process_index())
                )
            # All trainers must agree on the step-PRNG base seed when the
            # program has none (dropout/random ops would diverge).
            from jax.experimental import multihost_utils

            self._base_seed = int(
                multihost_utils.broadcast_one_to_all(
                    np.int64(self._base_seed)
                )
            )

        devices = jax.devices()
        non_cpu = [d for d in devices if d.platform != "cpu"]
        pool = non_cpu if (use_tpu and non_cpu) else devices
        n = num_devices or len(pool)
        # Program-level pipeline parallelism: cut the Program into S
        # stages over the mesh's pipe axis (parallel/program_pipeline.py);
        # remaining devices form the data axis (pipeline x dp).
        self._pipeline_stages = pipeline_stages
        self._pipeline_micro = pipeline_microbatches or (
            2 * pipeline_stages if pipeline_stages else None)
        self._pipeline_entry = None
        if pipeline_stages:
            if n % pipeline_stages:
                raise ValueError(
                    "pipeline_stages=%d must divide the device count %d"
                    % (pipeline_stages, n))
            if self._num_trainers > 1:
                raise NotImplementedError(
                    "pipeline_stages does not yet compose with "
                    "num_trainers>1 (multi-host feed assembly is only "
                    "wired for the data-parallel path)")
            if fsdp is not None or tp is not None:
                raise NotImplementedError(
                    "pipeline_stages does not yet compose with a "
                    "fsdp/tp planning mesh (pipe-axis composition is an "
                    "open ROADMAP item); drop fsdp=/tp= or the pipeline")
            self.mesh = build_mesh(
                num_devices=n, data=n // pipeline_stages,
                pipe=pipeline_stages, devices=pool)
        elif fsdp is not None or tp is not None:
            # explicit planning mesh: the sharding transpiler derives the
            # full var->PartitionSpec plan over (data, fsdp, tp)
            self.mesh = build_mesh(
                num_devices=n, fsdp=fsdp, tp=tp, devices=pool)
        elif (self._build_strategy.reduce_strategy
              == BuildStrategy.ReduceStrategy.Reduce
              and not model_sharded_vars
              and not _names_legacy_axes(sharding_overrides)):
            # Reduce = "fsdp over the derived plan": batch shards over the
            # fsdp axis exactly as it sharded over "data" before, but the
            # per-var layouts now come from the op graph (conv filters
            # out-channel-sharded, norm stats replicated, tiny biases
            # whole) instead of blanket dim-0 sharding. Legacy-axis
            # overrides / model_sharded_vars keep the old policy.
            self.mesh = build_mesh(num_devices=n, fsdp=n, devices=pool)
        else:
            self.mesh = build_mesh(num_devices=n, devices=pool)
        self._model_sharded_vars = set(model_sharded_vars or ())
        # Tensor-parallel layout control: var name -> PartitionSpec (or a
        # plain tuple of axis names / None). GSPMD inserts the matching
        # collectives (all-gather for column-parallel, psum for
        # row-parallel) — the scaling-book recipe. Under a planning mesh
        # these are OVERRIDES on top of the derived plan (S001-validated);
        # under a legacy mesh they are the whole tensor-parallel story.
        self._sharding_overrides = dict(sharding_overrides or {})
        self._derived_plans = {}  # plan cache: one derivation per compile key
        self._active_plan = None  # plan of the latest compiled executable
        self._overrides_checked = set()  # S001 once per (mesh sig)
        if share_vars_from is not None:
            self._scope = share_vars_from._scope

    @property
    def device_count(self):
        return int(np.prod(list(self.mesh.shape.values())))

    def _policy(self, state_shapes, feed_specs=None):
        if "fsdp" in self.mesh.shape or "tp" in self.mesh.shape:
            return self._derived_policy(state_shapes, feed_specs)
        self._check_overrides_s001()
        strategy = (
            "reduce"
            if self._build_strategy.reduce_strategy
            == BuildStrategy.ReduceStrategy.Reduce
            else "all_reduce"
        )
        from jax.sharding import PartitionSpec

        overrides = {
            name: spec if isinstance(spec, PartitionSpec)
            else PartitionSpec(*spec)
            for name, spec in self._sharding_overrides.items()
        }
        return ShardingPolicy(
            self.mesh,
            strategy=strategy,
            state_shapes=state_shapes,
            model_sharded_vars=self._model_sharded_vars,
            overrides=overrides,
        )

    def _check_overrides_s001(self):
        """Rule S001 on the hand-written override surface (legacy path;
        the derived path validates inside derive_sharding): an override
        naming an unknown var, exceeding its rank, or referencing an axis
        absent from the mesh dies HERE as a rule-tagged Diagnostic, not
        as an opaque XLA shape error minutes into the compile."""
        if not self._sharding_overrides:
            return
        mesh_sig = tuple(sorted(self.mesh.shape.items()))
        if mesh_sig in self._overrides_checked:
            return
        from paddle_tpu.analysis.diagnostics import (
            ProgramVerifyError, at_or_above)
        from paddle_tpu.analysis.shard_check import check_sharding

        diags = check_sharding(
            self._program, self.mesh, self._sharding_overrides,
            origin="sharding_overrides")
        errors = at_or_above(diags, "error")
        if errors:
            raise ProgramVerifyError(errors, origin="ParallelExecutor")
        self._overrides_checked.add(mesh_sig)

    def _derived_policy(self, state_shapes, feed_specs=None):
        """The sharding transpiler path: derive (and cache) the plan for
        this (program, mesh, feed shapes, overrides) key, export its
        per-axis collective-byte gauges, and wrap it in the policy
        interface the CompiledProgram consumes."""
        from paddle_tpu.parallel.sharding import (
            DerivedShardingPolicy,
            derive_sharding,
            record_collective_bytes,
        )

        feed_shapes = {n: s for n, (s, _d) in (feed_specs or {}).items()}
        key = (
            program_fingerprint(self._program),
            tuple(sorted(self.mesh.shape.items())),
            tuple(sorted(feed_shapes.items())),
            tuple(sorted((k, str(v))
                         for k, v in self._sharding_overrides.items())),
        )
        plan = self._derived_plans.get(key)
        if plan is None:
            plan = derive_sharding(
                self._program, self.mesh,
                overrides=self._sharding_overrides or None,
                feed_shapes=feed_shapes)
            record_collective_bytes(plan)
            # bounded FIFO: evict oldest, keep the hot rotation (same
            # idiom as observability.memory's plan registry)
            while len(self._derived_plans) >= 16:
                self._derived_plans.pop(next(iter(self._derived_plans)))
            self._derived_plans[key] = plan
        return DerivedShardingPolicy(self.mesh, plan,
                                     state_shapes=state_shapes)

    def sharding_plan(self, feed_shapes=None):
        """The derived :class:`parallel.sharding.ShardingPlan` this
        executor compiled with — or, before the first run, the plan it
        *would* compile with (planning meshes only; None under a legacy
        mesh) — inspectable without running anything:
        ``debugger.program_to_code`` shows the stamped per-var specs.
        After a run, the no-argument form returns the compiled plan
        verbatim; pass ``feed_shapes`` to derive a what-if plan for
        different feeds (this re-stamps the program annotations)."""
        if not ("fsdp" in self.mesh.shape or "tp" in self.mesh.shape):
            return None
        if feed_shapes is None and self._active_plan is not None:
            return self._active_plan
        feed_specs = {n: (tuple(s), "") for n, s in
                      (feed_shapes or {}).items()}
        return self._derived_policy(
            self._collect_state_shapes(), feed_specs).derived

    def _get_compiled(self, feed_specs, fetch_names):
        scope_names = set(self._scope.local_var_names())
        mesh_sig = tuple(sorted(self.mesh.shape.items()))
        key = (
            # content hash (core/fingerprint.py), not _version alone: two
            # structurally identical programs share the sharded compile
            program_fingerprint(self._program),
            tuple(sorted((n, s, d) for n, (s, d) in feed_specs.items())),
            tuple(fetch_names),
            frozenset(scope_names),
            trace_flags_key(),
            mesh_sig,
        )
        cp = self._cache.get(key)
        if cp is not None:
            exec_cache.record_trace_hit()
            return cp
        # instance miss: consult the process-global registry under a key
        # extended with the mesh's device identity and every policy
        # input the instance key could hold constant — a REBUILT
        # executor (elastic reshape back to a seen world size, Predictor
        # clones, tests constructing fresh PEs) must only reuse an
        # executable whose shardings were derived from identical inputs
        state_shapes = self._collect_state_shapes()
        shared_key = key + (
            tuple(d.id for d in self.mesh.devices.flat),
            self._build_strategy.reduce_strategy,
            tuple(sorted(self._model_sharded_vars)),
            tuple(sorted((k, str(v))
                         for k, v in self._sharding_overrides.items())),
            tuple(sorted(state_shapes.items())),
        )
        with _shared_lock:
            cp = _shared_compiled.get(shared_key)
            if cp is not None:
                _shared_compiled.move_to_end(shared_key)
        if cp is not None:
            exec_cache.record_trace_hit()
            # the reused executable carries the plan it compiled with —
            # this instance adopts it as its active plan
            self._active_plan = getattr(cp, "_sharding_plan", None)
            self._cache[key] = cp
            return cp
        # compile OUTSIDE the registry lock: an XLA compile (plus any
        # retry backoff) must never stall other executors' unrelated
        # cache misses. Two threads racing the same key pay a duplicate
        # compile — exactly what the old per-instance caching always
        # paid — and the loser adopts the winner's entry below.
        exec_cache.record_trace_miss()
        exec_cache.configure()
        _explain.record_compile({
            "program": key[0],
            "feed_specs": tuple(sorted(
                (n, (s, d)) for n, (s, d) in feed_specs.items())),
            "fetch_names": tuple(fetch_names),
            "scope_signature": frozenset(scope_names),
            "flags": key[4],
            "device": "mesh:%s" % (mesh_sig,),
            "mode": "gspmd",
        })
        policy = self._policy(state_shapes, feed_specs)
        self._active_plan = getattr(policy, "derived", None)

        def _build():
            if _chaos.ENABLED:
                _chaos.fault("exec.compile")
            return CompiledProgram(
                self._program,
                feed_specs,
                fetch_names,
                scope_names,
                is_test=self._program._is_test,
                shardings=policy,
            )

        cp = _retry.call(_build, origin="ParallelExecutor.compile")
        # the derived plan rides the executable: memory planning divides
        # predicted bytes by each var's shard factor, and captures/
        # benches read the summary without re-deriving
        cp._sharding_plan = getattr(policy, "derived", None)
        cp._exec_cache_key = executable_key(
            self._program, feed_specs, fetch_names, scope_names,
            extra=("gspmd", mesh_sig,
                   self._build_strategy.reduce_strategy,
                   tuple(sorted(self._model_sharded_vars)),
                   tuple(sorted(
                       (k, str(v))
                       for k, v in self._sharding_overrides.items()
                   ))),
        )
        with _shared_lock:
            existing = _shared_compiled.get(shared_key)
            if existing is not None:
                cp = existing  # a concurrent builder won; use its entry
                self._active_plan = getattr(cp, "_sharding_plan", None)
            else:
                _shared_compiled[shared_key] = cp
                while len(_shared_compiled) > _SHARED_CAP:
                    _shared_compiled.popitem(last=False)
        self._cache[key] = cp
        return cp

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        # forensics shell (same contract as Executor.run): armed for the
        # watchdog — a multichip step that never returns is THE hang this
        # layer exists for — and any escaping exception lands in the
        # black box with this origin before propagating
        with _blackbox.guard("ParallelExecutor.run"):
            return self._run_impl(fetch_list, feed, feed_dict, return_numpy)

    def _run_impl(self, fetch_list, feed=None, feed_dict=None,
                  return_numpy=True):
        telem = _telemetry.ENABLED
        prof = _profiler.enabled()
        t0 = time.perf_counter() if (telem or prof) else 0.0
        feed = feed if feed is not None else (feed_dict or {})
        if self._pipeline_stages:
            fetches = self._run_pipeline(fetch_list, feed, return_numpy)
            if telem:
                # per-stage occupancy: the bubble fraction of the GPipe
                # schedule, one labeled series per stage
                _telemetry.record_pipeline_occupancy(
                    self._pipeline_stages, self._pipeline_micro)
                _telemetry.record_step(
                    "pipeline", time.perf_counter() - t0,
                    fingerprint=program_fingerprint(self._program))
            return fetches
        sp = _stepprof.begin("parallel") if _stepprof.ENABLED else None
        if sp is not None:
            sp.enter("feed")
        if isinstance(feed, list):
            # per-device feed dicts (fluid API) -> concat along batch.
            merged = {}
            for name in feed[0]:
                merged[name] = np.concatenate(
                    [np.asarray(d[name]) for d in feed], axis=0
                )
            feed = merged

        feeds = {}
        feed_specs = {}
        for name, value in feed.items():
            arr = (
                np.asarray(value.numpy())
                if isinstance(value, LoDTensor)
                else np.asarray(value)
            )
            if self._num_trainers > 1:
                # Each trainer feeds its LOCAL batch shard; assemble the
                # global array (this is the FeedAndSplitTensorIntoLocalScopes
                # role, parallel_executor.cc:286, inverted: shards in,
                # global view out). Non-batch feeds replicate (each trainer
                # must pass the full value) per the policy's shape check —
                # the global dim0 for sharded feeds is num_trainers * local.
                policy = self._policy(self._collect_state_shapes())
                gshape = list(arr.shape)
                if gshape:
                    gshape[0] *= self._num_trainers
                sh = policy.feed_sharding(name, shape=tuple(gshape))
                if sh.is_fully_replicated:
                    # every trainer passes the identical full value
                    host = arr
                    arr = jax.make_array_from_callback(
                        host.shape, sh, lambda idx: host[idx]
                    )
                else:
                    arr = jax.make_array_from_process_local_data(sh, arr)
            feeds[name] = arr
            feed_specs[name] = (tuple(arr.shape), str(arr.dtype))

        if sp is not None:
            sp.exit()
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        if sp is not None:
            sp.enter("compile")
        cp = self._get_compiled(feed_specs, fetch_names)
        if sp is not None:
            sp.exit()
            # input assembly continues: state gather (+ reshard) and
            # step-key derivation run on the host clock before dispatch
            sp.enter("feed")

        state = {}
        for n in cp.state_in:
            v = self._scope.find_var(n)
            if v is None or v.value is None:
                raise RuntimeError(
                    "persistable var %r not initialized (run startup first)" % n
                )
            val = v.value
            # State initialized by the single-device startup Executor is
            # committed to one device; donated jit args must already carry
            # the mesh sharding, so reshard explicitly (BCastParamsToDevices
            # role, parallel_executor.cc:180).
            if isinstance(val, jax.Array):
                val = self._ensure_sharded(val, cp.shardings.state_sharding(n))
            state[n] = val

        self._run_counter += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(self._program.random_seed or self._base_seed),
            self._run_counter,
        )
        if sp is not None:
            sp.exit()
            # opens before the pre-dispatch work (cost snapshot,
            # blackbox record): host dispatch overhead is charged to
            # dispatch, not left in the unattributed residual
            sp.enter("dispatch")
            sp.pre_dispatch(cp, state, feeds, key, self._program)
        flops_avals = None
        mem_dev = None
        if telem:
            fingerprint = _telemetry.executable_fingerprint(
                cp, self._program)
            flops_avals = _telemetry.capture_step_avals(
                cp, state, feeds, key)
            _telemetry.record_device_transfer(
                self._feed_bytes_by_device(cp, feeds))
            # HBM ledger: feeds/fetches (global sharded arrays) book
            # under one 'mesh' label; STATE books per device from real
            # shard sizes below, so the ledger shows each chip's
            # param/opt_state residency under the derived plan
            mem_dev = "mesh"
            _memory.track_feeds(feeds, mem_dev)
            if not getattr(cp, "_memory_plan_done", False):
                shard_factors = mesh_devices = None
                if getattr(cp, "_sharding_plan", None) is not None:
                    from paddle_tpu.parallel.sharding import (
                        plan_shard_factors)

                    shard_factors = plan_shard_factors(cp._sharding_plan)
                    mesh_devices = self.device_count
                _memory.register_plan_for(cp, self._program, feed_specs,
                                          fingerprint,
                                          shard_factors=shard_factors,
                                          mesh_devices=mesh_devices)
        if _blackbox.ENABLED:
            _blackbox.record_dispatch(
                "ParallelExecutor.run", feed_specs=feed_specs,
                fetch_names=fetch_names,
                fingerprint=getattr(cp, "_exec_cache_key", None),
                mesh=dict(self.mesh.shape))
        t_disp = time.perf_counter() if telem else 0.0
        from paddle_tpu.executor import Executor as _Executor

        new_state, fetches = _Executor._dispatch(
            cp, state, feeds, key, origin="ParallelExecutor.dispatch")
        if sp is not None:
            sp.exit()
            sp.enter("fetch")
        for n, val in new_state.items():
            self._scope.set_value(n, val)
        if telem:
            # per-device ledger entries from the REAL shard sizes: a
            # param fsdp-sharded 4 ways books ~1/4 of its bytes on each
            # device label; replicated state books full bytes on every
            # device — paddle_tpu_hbm_live_bytes{device,kind} shows the
            # derived plan's memory win directly
            _memory.track_state_sharded(cp, self._program, new_state,
                                        fallback_device=mem_dev)
            _memory.track_fetches(cp.fetch_names, fetches, mem_dev)
            _memory.drop_feeds(feeds, mem_dev)
        if sp is not None:
            # the fetch bracket closes AFTER the ledger writeback (see
            # Executor.run): co-enabled telemetry's accounting is
            # output handling, not unattributed residual
            sp.exit()
        device_times = None
        if telem and return_numpy:
            # per-device dispatch->ready latency, measured on the live
            # global arrays BEFORE any host materialization — the
            # straggler/imbalance signal. Only on the return_numpy path,
            # which syncs anyway: blocking per-shard under
            # return_numpy=False would turn an async dispatch into a
            # full per-step device sync and distort the thing measured.
            # This blocks on device shards, so it IS device wait — the
            # bracket charges it there, and the later per-fetch
            # block_until_ready returns instantly having been paid here
            if sp is not None:
                sp.enter("device")
            device_times = _telemetry.device_step_times(
                list(fetches) + list(new_state.values()), t_disp)
            if sp is not None:
                sp.exit()
        if return_numpy:
            if sp is not None:
                sp.enter("device")
                with _stepprof.device_annotation():
                    for _f in fetches:
                        if hasattr(_f, "block_until_ready"):
                            _f.block_until_ready()
                sp.exit()
                sp.enter("fetch")
            try:
                fetches = [self._fetch_to_numpy(f) for f in fetches]
            except Exception as exc:
                # allocator deaths can surface at the host read, not the
                # dispatch — same M001 forensics as Executor._dispatch
                if _memory.is_oom(exc) and not isinstance(
                        exc, _memory.MemoryExhaustedError):
                    _memory.enrich_and_raise(
                        exc, origin="ParallelExecutor.fetch")
                raise
            if sp is not None:
                sp.exit()
        if sp is not None:
            # span closes before telemetry's record-keeping tail (see
            # Executor.run): per-step wall is comparable across
            # observer configurations
            _stepprof.finish(sp, feeds=feeds, fetches=fetches)
        if telem:
            _memory.drop_fetches(cp.fetch_names, mem_dev)
        if telem or prof:
            t1 = time.perf_counter()
            if telem:
                _telemetry.record_step(
                    "parallel", t1 - t0,
                    feed_bytes=sum(
                        getattr(a, "nbytes", 0) for a in feeds.values()),
                    fetch_bytes=sum(
                        getattr(f, "nbytes", 0) for f in fetches
                        if hasattr(f, "nbytes")),
                    fingerprint=fingerprint,
                    device_times=device_times)
                if flops_avals is not None:
                    _telemetry.register_flops_from_avals(
                        cp, fingerprint, flops_avals)
            if prof:
                _profiler.record_span("parallel_executor.run", t0, t1)
        return fetches

    def _feed_bytes_by_device(self, cp, feeds):
        """{device label: feed bytes} for one step. Global jax arrays
        report their real addressable shards; host numpy feeds (the
        single-process path — jit shards them at dispatch) are priced
        from the policy's feed sharding, which is what jit applies."""
        from paddle_tpu.parallel.mesh import device_label

        per_dev = {}
        for name, arr in feeds.items():
            if isinstance(arr, jax.Array):
                try:
                    for sh in arr.addressable_shards:
                        lbl = device_label(sh.device)
                        per_dev[lbl] = per_dev.get(lbl, 0) + int(
                            getattr(sh.data, "nbytes", 0))
                    continue
                except Exception:
                    pass
            try:
                sharding = cp.shardings.feed_sharding(
                    name, shape=tuple(arr.shape))
                shard_shape = sharding.shard_shape(tuple(arr.shape))
                nbytes = int(np.prod(shard_shape, dtype=np.int64)
                             ) * arr.dtype.itemsize if shard_shape else \
                    arr.dtype.itemsize
                for d in sharding.addressable_devices:
                    lbl = device_label(d)
                    per_dev[lbl] = per_dev.get(lbl, 0) + nbytes
            except Exception:
                continue
        return per_dev

    # -- program-level pipeline path ---------------------------------------
    def _run_pipeline(self, fetch_list, feed, return_numpy):
        from paddle_tpu.parallel.program_pipeline import PipelinedProgram

        if isinstance(feed, list):
            feed = {
                name: np.concatenate(
                    [np.asarray(d[name]) for d in feed], axis=0)
                for name in feed[0]
            }
        fetch_names = [
            v.name if isinstance(v, framework.Variable) else str(v)
            for v in fetch_list
        ]
        if self._loss_name and fetch_names and fetch_names != [
                self._loss_name]:
            raise ValueError(
                "pipeline runs fetch only the loss (%r), got %r — params "
                "live packed per stage; use pipeline_sync_scope() to "
                "inspect them" % (self._loss_name, fetch_names))
        feeds = {}
        feed_specs = {}
        for name, value in feed.items():
            arr = (
                np.asarray(value.numpy())
                if isinstance(value, LoDTensor)
                else np.asarray(value)
            )
            feeds[name] = arr
            feed_specs[name] = (tuple(arr.shape), str(arr.dtype))
        sig = (program_fingerprint(self._program),
               tuple(sorted(feed_specs.items())), trace_flags_key())
        entry = self._pipeline_entry
        if entry is None or entry["sig"] != sig:
            if entry is not None:
                # the executable is stale (new feed shapes or program
                # version) but the TRAINED packed state is not: flush it
                # to the scope so the rebuilt entry repacks current values
                self.pipeline_sync_scope()
            pp = PipelinedProgram(
                self._program,
                self._loss_name,
                feed_specs,
                self.mesh,
                self._pipeline_micro,
                batch_axis="data" if self.mesh.shape["data"] > 1 else None,
            )
            entry = {"pp": pp, "state": pp.pack_from_scope(self._scope),
                     "sig": sig}
            self._pipeline_entry = entry
        pp = entry["pp"]
        params, accs, scalars = entry["state"]
        self._run_counter += 1
        key = jax.random.fold_in(
            jax.random.PRNGKey(self._program.random_seed or self._base_seed),
            self._run_counter,
        )
        params, accs, scalars, loss = pp.jitted(
            params, accs, scalars, feeds, key)
        entry["state"] = (params, accs, scalars)
        # scalar persistables (lr counters, beta pows) stay scope-visible
        for n, val in scalars.items():
            self._scope.set_value(n, val)
        if not fetch_names:
            return []
        if return_numpy:
            return [np.reshape(np.asarray(loss), (1,))]
        return [jnp.reshape(loss, (1,))]

    def pipeline_sync_scope(self):
        """Unpack the pipeline's packed params/accumulators back into their
        per-name scope vars (so save_persistables etc. see current values)."""
        entry = self._pipeline_entry
        if entry is not None:
            params, accs, _ = entry["state"]
            entry["pp"].unpack_to_scope(self._scope, params, accs)

    def _ensure_sharded(self, val, target):
        """Reshard ``val`` to ``target`` if it is not already equivalent."""
        try:
            if val.sharding.is_equivalent_to(target, val.ndim):
                return val
        except Exception:
            pass
        if (
            self._num_trainers > 1
            and not getattr(target, "is_fully_addressable", True)
            and getattr(val, "is_fully_addressable", True)
        ):
            # First mesh placement of locally-initialized state: broadcast
            # rank 0's value so every trainer materializes shards of the
            # SAME array even when startup init was unseeded — the actual
            # BCastParamsToDevices (parallel_executor.cc:180).
            from jax.experimental import multihost_utils

            host = multihost_utils.broadcast_one_to_all(np.asarray(val))
            host = np.asarray(host)
            return jax.make_array_from_callback(
                host.shape, target, lambda idx: host[idx]
            )
        # Already-global arrays reshard device-side (XLA collectives).
        return jax.device_put(val, target)

    def _fetch_to_numpy(self, f):
        """Fetched global arrays: fully-addressable values read directly;
        otherwise stitch this process's addressable shards (the reference
        likewise fetches trainer-local values in NCCL2 mode)."""
        if not (isinstance(f, jax.Array) and not f.is_fully_addressable):
            return np.asarray(f)
        shards = {}
        for s in f.addressable_shards:
            key = tuple(
                (sl.start or 0, sl.stop) for sl in s.index
            )
            shards.setdefault(key, np.asarray(s.data))
        if len(shards) == 1:
            return next(iter(shards.values()))
        keys = sorted(shards)
        axis = next(
            i for i in range(len(keys[0]))
            if len({k[i] for k in keys}) > 1
        )
        ordered = [shards[k] for k in sorted(shards, key=lambda k: k[axis])]
        return np.concatenate(ordered, axis=axis)

    def _collect_state_shapes(self):
        state_shapes = {}
        for n in self._scope.local_var_names():
            v = self._scope.get_value(n)
            if v is not None and hasattr(v, "shape"):
                state_shapes[n] = tuple(v.shape)
        return state_shapes

    def bcast_params(self):
        """BCastParamsToDevices parity (parallel_executor.cc:180): eagerly
        reshard every initialized scope var onto the mesh per the current
        ShardingPolicy (jit would otherwise do this lazily on first run)."""
        policy = self._policy(self._collect_state_shapes())
        for n in sorted(policy.state_shapes):
            v = self._scope.get_value(n)
            if isinstance(v, jax.Array):
                self._scope.set_value(
                    n, self._ensure_sharded(v, policy.state_sharding(n))
                )
