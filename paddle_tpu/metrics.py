"""Python-side metric accumulators (python/paddle/fluid/metrics.py parity)."""

import numpy as np

__all__ = [
    "MetricBase",
    "CompositeMetric",
    "Precision",
    "Recall",
    "Accuracy",
    "ChunkEvaluator",
    "EditDistance",
    "Auc",
    "DetectionMAP",
]


class MetricBase(object):
    def __init__(self, name):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        """Zero every public accumulator in place. Subclasses keep their
        running state as public attributes, so the base reset can restart
        an epoch without knowing each metric's fields: numbers restart at
        zero, arrays at zeros of the same shape, anything else is cleared."""
        for attr in list(vars(self)):
            if attr.startswith("_"):
                continue
            value = getattr(self, attr)
            if callable(value):
                continue
            if isinstance(value, np.ndarray):
                fresh = np.zeros_like(value)
            elif isinstance(value, (int, float)):
                fresh = type(value)(0)
            else:
                fresh = None
            setattr(self, attr, fresh)

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).reshape(-1)[0]
        )

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances != 0))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data updated into EditDistance")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super(Auc, self).__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._stat_pos = np.zeros(num_thresholds + 1)
        self._stat_neg = np.zeros(num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        for i, label in enumerate(labels):
            pos_prob = preds[i, 1] if preds.ndim == 2 else preds[i]
            bin_idx = int(pos_prob * self._num_thresholds)
            if label:
                self._stat_pos[bin_idx] += 1.0
            else:
                self._stat_neg[bin_idx] += 1.0

    @staticmethod
    def trapezoid_area(x1, x2, y1, y2):
        return abs(x1 - x2) * (y1 + y2) / 2.0

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        idx = self._num_thresholds
        while idx >= 0:
            tot_pos_prev = tot_pos
            tot_neg_prev = tot_neg
            tot_pos += self._stat_pos[idx]
            tot_neg += self._stat_neg[idx]
            auc += self.trapezoid_area(
                tot_neg, tot_neg_prev, tot_pos, tot_pos_prev
            )
            idx -= 1
        return (
            auc / tot_pos / tot_neg if tot_pos > 0.0 and tot_neg > 0.0 else 0.0
        )


class DetectionMAP(MetricBase):
    """Accumulative mean-Average-Precision across batches (host side).

    Reference parity: python/paddle/fluid/metrics.py DetectionMAP /
    detection_map_op.cc accumulative states. The in-graph
    ``layers.detection_map`` op scores ONE batch; this class accumulates
    padded detections + dense ground truth over many batches and computes
    the epoch mAP with the same greedy-matching + integral/11point rules.

    update() takes the padded-batch layout (docs/LOD_DESIGN.md):
      detections [N, D, 6] (label, score, x1, y1, x2, y2), label -1 pads;
      gt_labels [N, G] int with -1 pads; gt_boxes [N, G, 4];
      difficult [N, G] optional.
    """

    def __init__(self, name=None, class_num=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral",
                 background_label=0):
        super(DetectionMAP, self).__init__(name)
        if class_num is None:
            raise ValueError("DetectionMAP requires class_num")
        self._class_num = class_num
        self._overlap_threshold = overlap_threshold
        self._evaluate_difficult = evaluate_difficult
        self._ap_version = ap_version
        self._background_label = background_label
        self.reset()

    def reset(self):
        # per image: (det [d,6], gt_label [g], gt_box [g,4], difficult [g])
        self._images = []

    def update(self, detections, gt_labels, gt_boxes, difficult=None):
        det = np.asarray(detections)
        gl = np.asarray(gt_labels)
        gb = np.asarray(gt_boxes)
        dif = (np.asarray(difficult) if difficult is not None
               else np.zeros_like(gl, dtype=np.float64))
        for i in range(det.shape[0]):
            dv = det[i][det[i, :, 0] >= 0]
            keep = gl[i] >= 0
            self._images.append(
                (dv.copy(), gl[i][keep].copy(), gb[i][keep].copy(),
                 dif[i][keep].astype(bool).copy()))

    @staticmethod
    def _iou(a, b):
        area_a = np.maximum(a[:, 2] - a[:, 0], 0) * np.maximum(
            a[:, 3] - a[:, 1], 0)
        area_b = np.maximum(b[:, 2] - b[:, 0], 0) * np.maximum(
            b[:, 3] - b[:, 1], 0)
        lt = np.maximum(a[:, None, :2], b[None, :, :2])
        rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = np.maximum(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / np.maximum(
            area_a[:, None] + area_b[None, :] - inter, 1e-10)

    def eval(self):
        thr = self._overlap_threshold
        aps = []
        for cls in range(self._class_num):
            if cls == self._background_label:
                continue
            # gather this class's detections (img idx, score, box) and gts
            rows = []
            n_pos = 0
            per_img_gt = []
            for img, (det, gl, gb, dif) in enumerate(self._images):
                sel = gl == cls
                countable = sel & (np.ones_like(sel)
                                   if self._evaluate_difficult else ~dif)
                n_pos += int(countable.sum())
                per_img_gt.append((gb[sel], dif[sel]))
                for d in det[det[:, 0].astype(int) == cls]:
                    rows.append((img, d[1], d[2:6]))
            if n_pos == 0:
                continue
            rows.sort(key=lambda r: -r[1])
            matched = [np.zeros(g.shape[0], bool) for g, _ in per_img_gt]
            tp, fp = [], []
            for img, _score, box in rows:
                g, dif = per_img_gt[img]
                if g.shape[0] == 0:
                    tp.append(0.0)
                    fp.append(1.0)
                    continue
                overlaps = self._iou(box[None], g)[0]
                best = int(np.argmax(overlaps))
                covered = overlaps[best] >= thr
                if covered and not self._evaluate_difficult and dif[best]:
                    continue  # ignored: neither TP nor FP
                hit = covered and not matched[img][best]
                if hit:
                    matched[img][best] = True
                tp.append(1.0 if hit else 0.0)
                fp.append(0.0 if hit else 1.0)
            if not tp:
                aps.append(0.0)
                continue
            ctp = np.cumsum(tp)
            cfp = np.cumsum(fp)
            precision = ctp / np.maximum(ctp + cfp, 1e-10)
            recall = ctp / n_pos
            if self._ap_version == "11point":
                ap = sum(
                    float(np.max(precision[recall >= r], initial=0.0))
                    for r in np.arange(0.0, 1.1, 0.1)
                ) / 11.0
            else:
                prev = np.concatenate([[0.0], recall[:-1]])
                ap = float(np.sum((recall - prev) * precision))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0
